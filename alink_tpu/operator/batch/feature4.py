"""Feature-selection and constrained-training long-tail.

Capability parity (reference: operator/batch/feature/
BinarySelectorTrainBatchOp.java / BinarySelectorPredictBatchOp.java /
RegressionSelectorTrainBatchOp.java / RegressionSelectorPredictBatchOp.java
and their Constrained* twins; finance/ConstrainedLinearRegTrainBatchOp.java /
ConstrainedLogisticRegressionTrainBatchOp.java /
ConstrainedDivergenceTrainBatchOp.java; feature/CrossFeatureTrainBatchOp
.java / CrossFeaturePredictBatchOp.java / HashCrossFeatureBatchOp.java /
CrossCandidateSelectorTrainBatchOp.java / AutoCrossTrainBatchOp.java;
finance/WoeTrainBatchOp.java / WoePredictBatchOp.java /
BinningTrainForScorecardBatchOp.java; statistics/MultiCollinearityBatchOp
.java; associationrule/GroupedFpGrowthBatchOp.java /
ApplyAssociationRuleBatchOp.java / ApplySequenceRuleBatchOp.java;
regression/GlmEvaluationBatchOp.java).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

import numpy as np

from ...common.exceptions import (
    AkIllegalArgumentException,
    AkIllegalDataException,
)
from ...common.linalg import SparseVector
from ...common.model import model_to_table, table_to_model
from ...common.mtable import AlinkTypes, MTable, TableSchema
from ...common.params import InValidator, MinValidator, ParamInfo
from ...mapper import (
    HasOutputCol,
    HasPredictionCol,
    HasReservedCols,
    HasSelectedCol,
    HasSelectedCols,
    Mapper,
    ModelMapper,
)
from .base import BatchOperator
from .associationrule import FpGrowthBatchOp
from .feature2 import AutoCrossBatchOp, BinningTrainBatchOp
from .linear import (
    BaseLinearModelTrainBatchOp,
    LinearRegTrainBatchOp,
    LogisticRegressionTrainBatchOp,
)
from .utils import MapBatchOp, ModelMapBatchOp, ModelTrainOpMixin


# ---------------------------------------------------------------------------
# constrained linear training
# ---------------------------------------------------------------------------


class _ConstrainedSolveMixin:
    """Routes the linear trainer's solver hook through the constrained
    optimizers. Constraints are linear, declared as JSON:
    ``{"A_eq": [[...]], "b_eq": [...], "A_ub": [[...]], "b_ub": [...]}``
    over the RAW weight vector incl. intercept slot — these ops default
    ``standardization`` OFF so the constraint means what the user wrote
    (a standardized fit would rescale the pinned weights at export)
    (reference: params/finance/HasConstraint.java — the reference encodes
    the same linear system in its ConstraintBetweenFeatures JSON)."""

    CONSTRAINT = ParamInfo("constraint", str, default=None,
                           desc="JSON linear constraint spec")
    CONSTRAINED_METHOD = ParamInfo(
        "constrainedMethod", str, default="alm",
        validator=InValidator("alm", "barrier"))

    def __init__(self, params=None, **kw):
        kw.setdefault("standardization", False)
        super().__init__(params, **kw)

    def _constraints(self):
        spec = self.get(self.CONSTRAINT)
        if not spec:
            return {}
        obj = json.loads(spec)
        out = {}
        for k in ("A_eq", "b_eq", "A_ub", "b_ub"):
            if k in obj:
                out[k] = np.asarray(obj[k], np.float32)
        return out

    def _solve(self, obj, X, y, sample_w):
        from ...optim import constrained_optimize

        cons = self._constraints()
        if not cons:
            return super()._solve(obj, X, y, sample_w)
        # same training knobs as the unconstrained path — adding a
        # constraint must not silently change unrelated behavior
        return constrained_optimize(
            obj, X, y, mesh=self.env.mesh,
            method=self.get(self.CONSTRAINED_METHOD),
            inner_max_iter=self.get(self.MAX_ITER),
            tol=self.get(self.EPSILON),
            sample_weights=sample_w,
            l1=self._effective_l1(), l2=self._effective_l2(),
            **cons)


class ConstrainedLogisticRegressionTrainBatchOp(_ConstrainedSolveMixin,
                                                LogisticRegressionTrainBatchOp):
    """(reference: operator/batch/finance/
    ConstrainedLogisticRegressionTrainBatchOp.java)"""


class ConstrainedLinearRegTrainBatchOp(_ConstrainedSolveMixin,
                                       LinearRegTrainBatchOp):
    """(reference: operator/batch/finance/
    ConstrainedLinearRegTrainBatchOp.java)"""


class ConstrainedDivergenceTrainBatchOp(ModelTrainOpMixin, BatchOperator,
                                        _ConstrainedSolveMixin):
    """Scorecard-style divergence training: maximize the squared separation
    of score means between classes over the pooled score variance,
    optionally under linear weight constraints (reference:
    operator/batch/finance/ConstrainedDivergenceTrainBatchOp.java — the
    divergence objective of scorecard fitting)."""

    FEATURE_COLS = ParamInfo("featureCols", list, default=None)
    LABEL_COL = ParamInfo("labelCol", str, optional=False)
    MAX_ITER = ParamInfo("maxIter", int, default=100)

    _min_inputs = 1
    _max_inputs = 1

    def _static_meta_keys(self, in_schema):
        return {"modelName": "LinearModel",
                "labelType": in_schema.type_of(self.get(self.LABEL_COL))}

    def _execute_impl(self, t: MTable) -> MTable:
        from ...mapper import resolve_feature_cols

        label_col = self.get(self.LABEL_COL)
        feature_cols = resolve_feature_cols(t, self, exclude=[label_col])
        X = t.to_numeric_block(feature_cols, dtype=np.float32)
        y_raw = np.asarray(t.col(label_col))
        labels = sorted(set(y_raw.tolist()), key=str)
        if len(labels) != 2:
            raise AkIllegalDataException(
                f"divergence training needs 2 label values, got {len(labels)}")
        pos = (y_raw == labels[0]).astype(np.float32)
        n, d = X.shape
        Xb = np.concatenate([X, np.ones((n, 1), np.float32)], axis=1)

        def divergence_obj(dim):
            # per-shard divergence, psum-averaged by the driver — exact on
            # one shard, a shard-average approximation under dp sharding
            import jax.numpy as jnp

            from ...optim.objfunc import ObjFunc

            def local_loss(w, Xj, yj, wt):
                s = Xj @ w
                p = yj * wt
                q = (1.0 - yj) * wt
                mu_p = (s * p).sum() / jnp.maximum(p.sum(), 1.0)
                mu_q = (s * q).sum() / jnp.maximum(q.sum(), 1.0)
                var_p = ((s - mu_p) ** 2 * p).sum() / jnp.maximum(p.sum(), 1.0)
                var_q = ((s - mu_q) ** 2 * q).sum() / jnp.maximum(q.sum(), 1.0)
                div = (mu_p - mu_q) ** 2 / (0.5 * (var_p + var_q) + 1e-6)
                # tiny L2 breaks the radial degeneracy (divergence is
                # scale-invariant); scale by the shard row count since the
                # driver divides by n
                return (-div + 1e-4 * (w @ w)) * Xj.shape[0]

            return ObjFunc(local_loss, dim)

        from ...optim import constrained_optimize, optimize

        cons = self._constraints()
        # w=0 is a stationary point of the divergence (all scores equal):
        # start from the class-mean direction instead
        mu_diff = (Xb[pos > 0.5].mean(0) - Xb[pos <= 0.5].mean(0))
        w0 = (mu_diff / max(np.linalg.norm(mu_diff), 1e-6)).astype(np.float32)
        if cons.get("A_eq") is not None and cons.get("A_ub") is None:
            # the divergence's scale-invariance defeats penalty methods
            # (shrinking w satisfies the penalty without changing the
            # objective) — equality constraints are solved EXACTLY in the
            # null space instead: w = N z, optimize z unconstrained
            A = np.atleast_2d(cons["A_eq"]).astype(np.float64)
            b = np.asarray(cons.get("b_eq", np.zeros(A.shape[0])),
                           np.float64)
            w_part = np.linalg.lstsq(A, b, rcond=None)[0]
            _u, sv, vt = np.linalg.svd(A)
            null = vt[np.sum(sv > 1e-10):].T  # (d+1, k)
            if null.shape[1] == 0:
                w = w_part.astype(np.float32)
                res = None
            else:
                Xz = (Xb @ null).astype(np.float32)
                shift = (Xb @ w_part).astype(np.float32)
                # scores = Xz z + shift with the shift coefficient FIXED at
                # 1 (append it as a column of the data, not of the weights)
                import jax.numpy as _jnp

                from ...optim.objfunc import ObjFunc as _ObjFunc

                def local_loss(z, Xj, yj, wt):
                    s = Xj[:, :-1] @ z + Xj[:, -1]
                    p = yj * wt
                    q = (1.0 - yj) * wt
                    mu_p = (s * p).sum() / _jnp.maximum(p.sum(), 1.0)
                    mu_q = (s * q).sum() / _jnp.maximum(q.sum(), 1.0)
                    var_p = ((s - mu_p) ** 2 * p).sum() / _jnp.maximum(
                        p.sum(), 1.0)
                    var_q = ((s - mu_q) ** 2 * q).sum() / _jnp.maximum(
                        q.sum(), 1.0)
                    div = (mu_p - mu_q) ** 2 / (
                        0.5 * (var_p + var_q) + 1e-6)
                    return (-div + 1e-4 * (z @ z)) * Xj.shape[0]

                obj2 = _ObjFunc(local_loss, null.shape[1])
                Xz2 = np.concatenate([Xz, shift[:, None]], axis=1)
                z0 = (null.T @ w0.astype(np.float64)).astype(np.float32)
                res = optimize(obj2, Xz2, pos, mesh=self.env.mesh, w0=z0,
                               max_iter=self.get(self.MAX_ITER))
                z = np.asarray(res.weights, np.float64)
                w = (null @ z + w_part).astype(np.float32)
        else:
            obj = divergence_obj(d + 1)
            if cons:
                res = constrained_optimize(
                    obj, Xb, pos, mesh=self.env.mesh,
                    method=self.get(self.CONSTRAINED_METHOD), w0=w0, **cons)
            else:
                res = optimize(obj, Xb, pos, mesh=self.env.mesh, w0=w0,
                               max_iter=self.get(self.MAX_ITER))
            w = res.weights
        # export at unit feature-weight norm when that cannot violate the
        # declared constraints (any inhomogeneous system pins a scale)
        rescalable = not cons or (
            cons.get("A_ub") is None
            and np.allclose(cons.get("b_eq", np.zeros(1)), 0.0))
        if rescalable:
            norm = float(np.linalg.norm(np.asarray(w)[:d]))
            if norm > 1e-9:
                w = np.asarray(w) / norm
        meta = {
            "modelName": "LinearModel",
            "linearModelType": "LinearReg",  # score = w·x + b serving
            "vectorCol": None,
            "featureCols": feature_cols,
            "labelCol": label_col,
            "labelType": t.schema.type_of(label_col),
            "labels": None,
            "hasIntercept": True,
            "dim": int(d),
            "loss": None if res is None else res.loss,
        }
        w = np.asarray(w)
        return model_to_table(meta, {
            "weights": w[:d].astype(np.float32),
            "intercept": np.asarray([w[d]], np.float32)})


# ---------------------------------------------------------------------------
# stepwise selectors
# ---------------------------------------------------------------------------


class _SelectorTrainBase(ModelTrainOpMixin, BatchOperator, HasSelectedCols):
    """Greedy forward selection: add the feature that most improves the
    training score until no gain or the cap (reference: feature/
    BaseStepwiseSelectorBatchOp.java forward stepwise)."""

    LABEL_COL = ParamInfo("labelCol", str, optional=False)
    MAX_SELECTED = ParamInfo("maxSelected", int, default=5,
                             aliases=("sMax", "k"),
                             validator=MinValidator(1))
    MIN_GAIN = ParamInfo("minGain", float, default=1e-4)

    _min_inputs = 1
    _max_inputs = 1
    _binary = True

    def _static_meta_keys(self, in_schema):
        return {"modelName": "SelectorModel"}

    def _fit_weights(self, X: np.ndarray, y: np.ndarray):
        """Least-squares fit of the working response — shared by the binary
        (linear-probability working model, like the reference's fast
        stepwise scoring) and regression selectors."""
        Xb = np.concatenate([X, np.ones((len(X), 1))], axis=1)
        w, *_ = np.linalg.lstsq(Xb, y, rcond=None)
        return w

    def _final_fit_weights(self, X: np.ndarray, y: np.ndarray):
        """Fit of the CHOSEN columns for the exported model — constrained
        variants override this (candidate scoring stays unconstrained)."""
        return self._fit_weights(X, y)

    def _score(self, X: np.ndarray, y: np.ndarray) -> float:
        w = self._fit_weights(X, y)
        Xb = np.concatenate([X, np.ones((len(X), 1))], axis=1)
        pred = Xb @ w
        if self._binary:
            # AUC of the score against the binary label
            order = np.argsort(pred)
            ranks = np.empty(len(pred))
            ranks[order] = np.arange(1, len(pred) + 1)
            pos = y > 0.5
            n_pos, n_neg = int(pos.sum()), int((~pos).sum())
            if n_pos == 0 or n_neg == 0:
                return 0.5
            return ((ranks[pos].sum() - n_pos * (n_pos + 1) / 2)
                    / (n_pos * n_neg))
        ss_res = float(((y - pred) ** 2).sum())
        ss_tot = float(((y - y.mean()) ** 2).sum()) + 1e-12
        return 1.0 - ss_res / ss_tot

    def _execute_impl(self, t: MTable) -> MTable:
        label_col = self.get(self.LABEL_COL)
        cols = list(self.get(HasSelectedCols.SELECTED_COLS) or
                    [c for c, tp in zip(t.names, t.schema.types)
                     if AlinkTypes.is_numeric(tp) and c != label_col])
        y_raw = np.asarray(t.col(label_col))
        if self._binary:
            labels = sorted(set(y_raw.tolist()), key=str)
            if len(labels) != 2:
                raise AkIllegalDataException(
                    f"binary selector needs 2 labels, got {len(labels)}")
            y = (y_raw == labels[1]).astype(np.float64)
        else:
            y = np.asarray(y_raw, np.float64)
        X_all = {c: np.asarray(t.col(c), np.float64) for c in cols}
        chosen: List[str] = []
        best_score = 0.5 if self._binary else 0.0
        history = []
        cap = min(int(self.get(self.MAX_SELECTED)), len(cols))
        min_gain = float(self.get(self.MIN_GAIN))
        while len(chosen) < cap:
            gains = []
            for c in cols:
                if c in chosen:
                    continue
                X = np.stack([X_all[k] for k in chosen + [c]], axis=1)
                gains.append((self._score(X, y), c))
            if not gains:
                break
            score, cand = max(gains)
            if score - best_score < min_gain and chosen:
                break
            chosen.append(cand)
            best_score = score
            history.append({"step": len(chosen), "col": cand,
                            "score": round(float(score), 6)})
        X = np.stack([X_all[k] for k in chosen], axis=1)
        w = self._final_fit_weights(X, y)
        meta = {
            "modelName": "SelectorModel",
            "binary": self._binary,
            "selectedCols": chosen,
            "labelCol": label_col,
            "score": float(best_score),
            "history": history,
        }
        return model_to_table(
            meta, {"weights": w[:-1].astype(np.float64),
                   "intercept": np.asarray([w[-1]], np.float64)})


class BinarySelectorTrainBatchOp(_SelectorTrainBase):
    """(reference: operator/batch/feature/BinarySelectorTrainBatchOp.java)"""

    _binary = True


class RegressionSelectorTrainBatchOp(_SelectorTrainBase):
    """(reference: operator/batch/feature/
    RegressionSelectorTrainBatchOp.java)"""

    _binary = False


class _SelectorPredictMapper(ModelMapper, HasPredictionCol, HasReservedCols):
    def load_model(self, model: MTable):
        self.meta, a = table_to_model(model)
        self.w = a["weights"]
        self.b = float(a["intercept"][0])
        return self

    def output_schema(self, input_schema):
        return self._append_result_schema(
            input_schema, [self.get(HasPredictionCol.PREDICTION_COL)],
            [AlinkTypes.DOUBLE])

    def map_table(self, t: MTable) -> MTable:
        X = np.stack([np.asarray(t.col(c), np.float64)
                      for c in self.meta["selectedCols"]], axis=1)
        score = X @ self.w + self.b
        oc = self.get(HasPredictionCol.PREDICTION_COL)
        return self._append_result(t, {oc: score}, {oc: AlinkTypes.DOUBLE})


class BinarySelectorPredictBatchOp(ModelMapBatchOp, HasPredictionCol,
                                   HasReservedCols):
    """(reference: operator/batch/feature/BinarySelectorPredictBatchOp.java)"""

    mapper_cls = _SelectorPredictMapper


class RegressionSelectorPredictBatchOp(BinarySelectorPredictBatchOp):
    """(reference: operator/batch/feature/
    RegressionSelectorPredictBatchOp.java)"""


class ConstrainedBinarySelectorTrainBatchOp(BinarySelectorTrainBatchOp,
                                            _ConstrainedSolveMixin):
    """Stepwise binary selection whose FINAL refit honors linear weight
    constraints; candidate scoring stays unconstrained. The constraint
    matrix columns map to the chosen columns in selection order plus the
    intercept slot (reference: operator/batch/feature/
    ConstrainedBinarySelectorTrainBatchOp.java)."""

    def _final_fit_weights(self, X, y):
        cons = self._constraints()
        if not cons:
            return super()._final_fit_weights(X, y)
        from ...optim import constrained_optimize, squared_obj

        Xb = np.concatenate([X, np.ones((len(X), 1))], axis=1)
        width = Xb.shape[1]
        for key in ("A_eq", "A_ub"):
            if key in cons and np.atleast_2d(cons[key]).shape[1] != width:
                raise AkIllegalArgumentException(
                    f"constraint {key} has "
                    f"{np.atleast_2d(cons[key]).shape[1]} columns but the "
                    f"final model has {width} (selected cols in order + "
                    f"intercept)")
        res = constrained_optimize(
            squared_obj(width), Xb.astype(np.float32),
            y.astype(np.float32), mesh=self.env.mesh,
            method=self.get(self.CONSTRAINED_METHOD), **cons)
        return np.asarray(res.weights, np.float64)


class ConstrainedRegSelectorTrainBatchOp(ConstrainedBinarySelectorTrainBatchOp):
    """(reference: operator/batch/feature/
    ConstrainedRegSelectorTrainBatchOp.java)"""

    _binary = False


class ConstrainedBinarySelectorPredictBatchOp(BinarySelectorPredictBatchOp):
    """(reference: operator/batch/feature/
    ConstrainedBinarySelectorPredictBatchOp.java)"""


class ConstrainedRegSelectorPredictBatchOp(BinarySelectorPredictBatchOp):
    """(reference: operator/batch/feature/
    ConstrainedRegSelectorPredictBatchOp.java)"""


# ---------------------------------------------------------------------------
# feature crosses
# ---------------------------------------------------------------------------


class CrossFeatureTrainBatchOp(ModelTrainOpMixin, BatchOperator,
                               HasSelectedCols):
    """Dictionary of observed value COMBINATIONS of the selected categorical
    columns (reference: operator/batch/feature/CrossFeatureTrainBatchOp
    .java)."""

    _min_inputs = 1
    _max_inputs = 1

    def _static_meta_keys(self, in_schema):
        return {"modelName": "CrossFeatureModel"}

    def _execute_impl(self, t: MTable) -> MTable:
        cols = list(self.get(HasSelectedCols.SELECTED_COLS))
        arrays = [np.asarray(t.col(c), object) for c in cols]
        combos: List[str] = []
        seen: Dict[str, int] = {}
        for i in range(t.num_rows):
            key = "\x01".join(str(a[i]) for a in arrays)
            if key not in seen:
                seen[key] = len(combos)
                combos.append(key)
        meta = {"modelName": "CrossFeatureModel", "selectedCols": cols,
                "combos": combos}
        return model_to_table(meta, {})


class CrossFeatureModelMapper(ModelMapper, HasOutputCol, HasReservedCols):
    """Combination → one-hot sparse vector (unseen → empty slot)."""

    def load_model(self, model: MTable):
        self.meta, _ = table_to_model(model)
        self.lut = {k: i for i, k in enumerate(self.meta["combos"])}
        return self

    def output_schema(self, input_schema):
        out = self.get(HasOutputCol.OUTPUT_COL) or "cross"
        return self._append_result_schema(
            input_schema, [out], [AlinkTypes.SPARSE_VECTOR])

    def map_table(self, t: MTable) -> MTable:
        cols = self.meta["selectedCols"]
        arrays = [np.asarray(t.col(c), object) for c in cols]
        dim = len(self.lut) + 1  # last slot = unseen
        vecs = np.empty(t.num_rows, object)
        for i in range(t.num_rows):
            key = "\x01".join(str(a[i]) for a in arrays)
            j = self.lut.get(key, dim - 1)
            vecs[i] = SparseVector(dim, np.asarray([j], np.int64),
                                   np.asarray([1.0]))
        out = self.get(HasOutputCol.OUTPUT_COL) or "cross"
        return self._append_result(
            t, {out: vecs}, {out: AlinkTypes.SPARSE_VECTOR})


class CrossFeaturePredictBatchOp(ModelMapBatchOp, HasOutputCol,
                                 HasReservedCols):
    """(reference: operator/batch/feature/CrossFeaturePredictBatchOp.java)"""

    mapper_cls = CrossFeatureModelMapper


class HashCrossFeatureMapper(Mapper, HasSelectedCols, HasOutputCol,
                             HasReservedCols):
    """Stateless cross: hash the value combination into numBuckets
    (reference: operator/batch/feature/HashCrossFeatureBatchOp.java)."""

    NUM_FEATURES = ParamInfo("numFeatures", int, default=262144,
                             aliases=("numBuckets",),
                             validator=MinValidator(2))

    def output_schema(self, input_schema):
        out = self.get(HasOutputCol.OUTPUT_COL) or "cross"
        return self._append_result_schema(
            input_schema, [out], [AlinkTypes.SPARSE_VECTOR])

    def map_table(self, t: MTable) -> MTable:
        from .similarity import _fnv64

        cols = list(self.get(HasSelectedCols.SELECTED_COLS))
        arrays = [np.asarray(t.col(c), object) for c in cols]
        dim = int(self.get(self.NUM_FEATURES))
        vecs = np.empty(t.num_rows, object)
        for i in range(t.num_rows):
            key = "\x01".join(str(a[i]) for a in arrays)
            j = _fnv64(key) % dim
            vecs[i] = SparseVector(dim, np.asarray([j], np.int64),
                                   np.asarray([1.0]))
        out = self.get(HasOutputCol.OUTPUT_COL) or "cross"
        return self._append_result(
            t, {out: vecs}, {out: AlinkTypes.SPARSE_VECTOR})


class HashCrossFeatureBatchOp(MapBatchOp, HasSelectedCols, HasOutputCol,
                              HasReservedCols):
    mapper_cls = HashCrossFeatureMapper
    NUM_FEATURES = HashCrossFeatureMapper.NUM_FEATURES


class CrossCandidateSelectorTrainBatchOp(ModelTrainOpMixin, BatchOperator):
    """Score candidate column crosses by chi-square against the label and
    keep the best (reference: operator/batch/feature/
    CrossCandidateSelectorTrainBatchOp.java)."""

    FEATURE_CANDIDATES = ParamInfo(
        "featureCandidates", list, optional=False,
        desc="list of column-name lists, one per candidate cross")
    LABEL_COL = ParamInfo("labelCol", str, optional=False)
    CROSS_FEATURE_NUMBER = ParamInfo("crossFeatureNumber", int, default=1,
                                     aliases=("topN",),
                                     validator=MinValidator(1))

    _min_inputs = 1
    _max_inputs = 1

    def _static_meta_keys(self, in_schema):
        return {"modelName": "CrossFeatureModel"}

    def _execute_impl(self, t: MTable) -> MTable:
        from .statistics import _contingency, chi_square_test

        label_col = self.get(self.LABEL_COL)
        y = t.col(label_col)
        scored = []
        for cand in self.get(self.FEATURE_CANDIDATES):
            cols = list(cand)
            arrays = [np.asarray(t.col(c), object) for c in cols]
            crossed = np.asarray(
                ["\x01".join(str(a[i]) for a in arrays)
                 for i in range(t.num_rows)], object)
            stat, _p, _dof = chi_square_test(_contingency(crossed, y))
            scored.append((float(stat), cols))
        scored.sort(key=lambda s: -s[0])
        keep = scored[: self.get(self.CROSS_FEATURE_NUMBER)]
        # train a combo dictionary for EVERY kept cross; the predict mapper
        # concatenates their one-hots
        crosses = []
        for _stat, cols in keep:
            inner_model = CrossFeatureTrainBatchOp(
                selectedCols=cols)._execute_impl(t)
            inner_meta, _ = table_to_model(inner_model)
            crosses.append({"cols": cols, "combos": inner_meta["combos"]})
        meta = {"modelName": "CrossFeatureModel",
                # single-cross fields kept for CrossFeatureModelMapper compat
                "selectedCols": crosses[0]["cols"],
                "combos": crosses[0]["combos"],
                "crosses": crosses,
                "candidates": [{"cols": c, "chi2": s} for s, c in scored]}
        return model_to_table(meta, {})


class CrossCandidateSelectorModelMapper(CrossFeatureModelMapper):
    """Concatenated one-hot over ALL selected crosses."""

    def load_model(self, model: MTable):
        self.meta, _ = table_to_model(model)
        self.crosses = self.meta.get(
            "crosses", [{"cols": self.meta["selectedCols"],
                         "combos": self.meta["combos"]}])
        self.luts = [({k: i for i, k in enumerate(c["combos"])}, c["cols"])
                     for c in self.crosses]
        return self

    def map_table(self, t: MTable) -> MTable:
        dims = [len(lut) + 1 for lut, _ in self.luts]
        offsets = np.concatenate([[0], np.cumsum(dims)])
        total = int(offsets[-1])
        vecs = np.empty(t.num_rows, object)
        col_arrays = [
            ([np.asarray(t.col(c), object) for c in cols], lut)
            for lut, cols in self.luts]
        for i in range(t.num_rows):
            idx = []
            for ci, (arrays, lut) in enumerate(col_arrays):
                key = "\x01".join(str(a[i]) for a in arrays)
                idx.append(offsets[ci] + lut.get(key, dims[ci] - 1))
            sidx = np.asarray(sorted(idx), np.int64)
            vecs[i] = SparseVector(total, sidx, np.ones(len(sidx)))
        out = self.get(HasOutputCol.OUTPUT_COL) or "cross"
        return self._append_result(
            t, {out: vecs}, {out: AlinkTypes.SPARSE_VECTOR})


class CrossCandidateSelectorPredictBatchOp(CrossFeaturePredictBatchOp):
    """(reference: operator/batch/feature/
    CrossCandidateSelectorPredictBatchOp.java)"""

    mapper_cls = CrossCandidateSelectorModelMapper


class AutoCrossTrainBatchOp(AutoCrossBatchOp):
    """(reference: operator/batch/feature/AutoCrossTrainBatchOp.java)"""


class AutoCrossAlgoTrainBatchOp(AutoCrossBatchOp):
    """(reference: operator/batch/feature/AutoCrossAlgoTrainBatchOp.java)"""


class BaseCrossTrainBatchOp(CrossFeatureTrainBatchOp):
    """(reference: operator/batch/feature/BaseCrossTrainBatchOp.java)"""


# ---------------------------------------------------------------------------
# WOE
# ---------------------------------------------------------------------------


class WoeTrainBatchOp(ModelTrainOpMixin, BatchOperator, HasSelectedCols):
    """Per-CATEGORY weight of evidence against a binary label (reference:
    operator/batch/finance/WoeTrainBatchOp.java; the numeric-binning WOE
    lives in BinningTrainBatchOp)."""

    LABEL_COL = ParamInfo("labelCol", str, optional=False)
    POSITIVE_LABEL = ParamInfo("positiveLabelValueString", str, default=None,
                               aliases=("positiveValue",))

    _min_inputs = 1
    _max_inputs = 1

    def _static_meta_keys(self, in_schema):
        return {"modelName": "WoeModel"}

    def _execute_impl(self, t: MTable) -> MTable:
        label_col = self.get(self.LABEL_COL)
        cols = list(self.get(HasSelectedCols.SELECTED_COLS) or
                    [c for c in t.names if c != label_col])
        y_raw = np.asarray(t.col(label_col))
        pos_val = self.get(self.POSITIVE_LABEL)
        if pos_val is None:
            pos_val = str(sorted(set(y_raw.tolist()), key=str)[-1])
        pos = np.asarray([str(v) == pos_val for v in y_raw])
        n_pos = max(int(pos.sum()), 1)
        n_neg = max(int((~pos).sum()), 1)
        maps: Dict[str, Dict[str, float]] = {}
        ivs: Dict[str, float] = {}
        for c in cols:
            vals = np.asarray(t.col(c), object).astype(str)
            woe: Dict[str, float] = {}
            iv = 0.0
            for cat in np.unique(vals):
                mask = vals == cat
                p = (pos & mask).sum() + 0.5
                q = (~pos & mask).sum() + 0.5
                rate_p = p / n_pos
                rate_q = q / n_neg
                w = float(np.log(rate_p / rate_q))
                woe[str(cat)] = w
                iv += (rate_p - rate_q) * w
            maps[c] = woe
            ivs[c] = float(iv)
        meta = {"modelName": "WoeModel", "selectedCols": cols,
                "positiveValue": pos_val, "woe": maps, "iv": ivs}
        return model_to_table(meta, {})


class WoeModelMapper(ModelMapper, HasReservedCols, HasSelectedCols):
    def load_model(self, model: MTable):
        self.meta, _ = table_to_model(model)
        return self

    def output_schema(self, input_schema):
        names, types = list(input_schema.names), list(input_schema.types)
        for c in self.meta["selectedCols"]:
            types[names.index(c)] = AlinkTypes.DOUBLE
        return TableSchema(names, types)

    def map_table(self, t: MTable) -> MTable:
        out = t
        for c in self.meta["selectedCols"]:
            woe = self.meta["woe"][c]
            vals = np.asarray(t.col(c), object).astype(str)
            out = out.with_column(
                c, np.asarray([woe.get(v, 0.0) for v in vals], np.float64),
                AlinkTypes.DOUBLE)
        return out


class WoePredictBatchOp(ModelMapBatchOp, HasReservedCols, HasSelectedCols):
    """(reference: operator/batch/finance/WoePredictBatchOp.java)"""

    mapper_cls = WoeModelMapper


class BinningTrainForScorecardBatchOp(BinningTrainBatchOp):
    """Binning preset used by the scorecard flow (reference:
    operator/batch/finance/BinningTrainForScorecardBatchOp.java)."""


# ---------------------------------------------------------------------------
# multicollinearity
# ---------------------------------------------------------------------------


class MultiCollinearityBatchOp(BatchOperator, HasSelectedCols):
    """Variance inflation factors + condition number per feature
    (reference: operator/batch/statistics/MultiCollinearityBatchOp.java)."""

    _min_inputs = 1
    _max_inputs = 1

    def _execute_impl(self, t: MTable) -> MTable:
        cols = list(self.get(HasSelectedCols.SELECTED_COLS) or
                    [c for c, tp in zip(t.names, t.schema.types)
                     if AlinkTypes.is_numeric(tp)])
        X = t.to_numeric_block(cols, dtype=np.float64)
        Xc = X - X.mean(0)
        sd = Xc.std(0)
        sd = np.where(sd < 1e-12, 1.0, sd)
        Xn = Xc / sd
        corr = (Xn.T @ Xn) / max(len(X) - 1, 1)
        # VIF_j = diag(corr^-1)
        inv = np.linalg.pinv(corr)
        vif = np.clip(np.diag(inv), 1.0, None)
        evals = np.linalg.eigvalsh(corr)
        cond = float(np.sqrt(max(evals.max(), 1e-12)
                             / max(evals.min(), 1e-12)))
        rows = [(c, float(v), cond) for c, v in zip(cols, vif)]
        return MTable.from_rows(rows, self._out_schema(t.schema))

    def _out_schema(self, in_schema):
        return TableSchema(["feature", "VIF", "conditionNumber"],
                           [AlinkTypes.STRING, AlinkTypes.DOUBLE,
                            AlinkTypes.DOUBLE])


# ---------------------------------------------------------------------------
# association-rule long-tail
# ---------------------------------------------------------------------------


class GroupedFpGrowthBatchOp(BatchOperator, HasSelectedCol):
    """FpGrowth per group (reference: operator/batch/associationrule/
    GroupedFpGrowthBatchOp.java)."""

    GROUP_COL = ParamInfo("groupCol", str, optional=False)
    MIN_SUPPORT_PERCENT = FpGrowthBatchOp.MIN_SUPPORT_PERCENT
    MIN_SUPPORT_COUNT = FpGrowthBatchOp.MIN_SUPPORT_COUNT
    ITEM_DELIMITER = FpGrowthBatchOp.ITEM_DELIMITER
    MAX_PATTERN_LENGTH = FpGrowthBatchOp.MAX_PATTERN_LENGTH

    _min_inputs = 1
    _max_inputs = 1

    def _execute_impl(self, t: MTable) -> MTable:
        group_col = self.get(self.GROUP_COL)
        groups = np.asarray(t.col(group_col), object)
        parts = []
        inner_params = self.get_params().clone()
        for g in sorted(set(groups.tolist()), key=str):
            sub = t.filter_mask(groups == g)
            inner = FpGrowthBatchOp(inner_params.clone())
            res = inner._execute_impl(sub)
            if isinstance(res, tuple):  # (itemsets, [rules side output])
                res = res[0]
            res = res.with_column(
                group_col, np.asarray([g] * res.num_rows, object),
                t.schema.type_of(group_col))
            parts.append(res)
        return MTable.concat(parts)

    def _out_schema(self, in_schema):
        inner = FpGrowthBatchOp(self.get_params().clone())
        base = inner._out_schema(in_schema)
        group_col = self.get(self.GROUP_COL)
        return TableSchema(
            list(base.names) + [group_col],
            list(base.types) + [in_schema.type_of(group_col)])


class ApplyAssociationRuleBatchOp(ModelMapBatchOp, HasSelectedCol,
                                  HasOutputCol, HasReservedCols):
    """Apply mined rules to transactions: emit the consequents whose
    antecedents are contained in the row's item set
    (reference: operator/batch/associationrule/
    ApplyAssociationRuleBatchOp.java; ``link_from(rules, data)``)."""

    class _Mapper(ModelMapper, HasSelectedCol, HasOutputCol,
                  HasReservedCols):
        ITEM_DELIMITER = ParamInfo("itemDelimiter", str, default=",")

        def load_model(self, model: MTable):
            # rules table: antecedent, consequent (, support/confidence...)
            delim = self.get(self.ITEM_DELIMITER)
            ant = [set(str(v).split(delim))
                   for v in model.col(model.names[0])]
            cons = [str(v) for v in model.col(model.names[1])]
            self.rules = list(zip(ant, cons))
            return self

        def output_schema(self, input_schema):
            out = self.get(HasOutputCol.OUTPUT_COL) or "recommendations"
            return self._append_result_schema(
                input_schema, [out], [AlinkTypes.STRING])

        def map_table(self, t: MTable) -> MTable:
            delim = self.get(self.ITEM_DELIMITER)
            sel = self.get(HasSelectedCol.SELECTED_COL)
            out = self.get(HasOutputCol.OUTPUT_COL) or "recommendations"
            res = np.empty(t.num_rows, object)
            for i, v in enumerate(t.col(sel)):
                items = set(str(v).split(delim)) if v is not None else set()
                hits = sorted({c for a, c in self.rules
                               if a <= items and c not in items})
                res[i] = ",".join(hits)
            return self._append_result(
                t, {out: res}, {out: AlinkTypes.STRING})

    mapper_cls = _Mapper
    ITEM_DELIMITER = _Mapper.ITEM_DELIMITER


class ApplySequenceRuleBatchOp(ApplyAssociationRuleBatchOp):
    """Apply sequence rules: the antecedent must appear as a SUBSEQUENCE
    (order preserved) of the row's event sequence (reference:
    operator/batch/associationrule/ApplySequenceRuleBatchOp.java)."""

    class _Mapper(ApplyAssociationRuleBatchOp._Mapper):
        def load_model(self, model: MTable):
            delim = self.get(self.ITEM_DELIMITER)
            self.rules = [
                ([a for a in str(v).split(delim) if a], str(c))
                for v, c in zip(model.col(model.names[0]),
                                model.col(model.names[1]))]
            return self

        @staticmethod
        def _subseq(needle: List[str], hay: List[str]) -> bool:
            it = iter(hay)
            return all(any(x == h for h in it) for x in needle)

        def map_table(self, t: MTable) -> MTable:
            delim = self.get(self.ITEM_DELIMITER)
            sel = self.get(HasSelectedCol.SELECTED_COL)
            out = self.get(HasOutputCol.OUTPUT_COL) or "recommendations"
            res = np.empty(t.num_rows, object)
            for i, v in enumerate(t.col(sel)):
                seq = [x for x in str(v).split(delim)] if v is not None else []
                hits = sorted({c for a, c in self.rules
                               if self._subseq(a, seq) and c not in seq})
                res[i] = ",".join(hits)
            return self._append_result(
                t, {out: res}, {out: AlinkTypes.STRING})

    mapper_cls = _Mapper


# ---------------------------------------------------------------------------
# GLM evaluation
# ---------------------------------------------------------------------------


class GlmEvaluationBatchOp(BatchOperator):
    """Deviance/AIC diagnostics of a fitted GLM on a dataset
    (reference: operator/batch/regression/GlmEvaluationBatchOp.java);
    ``link_from(glm_model, data)``."""

    _min_inputs = 2
    _max_inputs = 2

    def _execute_impl(self, model: MTable, t: MTable) -> MTable:
        from .regression import GlmPredictBatchOp

        meta, _ = table_to_model(model)
        label_col = meta["labelCol"]
        pred = GlmPredictBatchOp(predictionCol="__glm_pred")
        scored = pred._execute_impl(model, t)
        y = np.asarray(t.col(label_col), np.float64)
        mu = np.asarray(scored.col("__glm_pred"), np.float64)
        family = str(meta.get("family", "gaussian")).lower()

        def deviance(mu_hat):
            eps = 1e-12
            if family == "poisson":
                return float(2.0 * np.sum(np.where(
                    y > 0,
                    y * np.log(np.maximum(y, eps) / np.maximum(mu_hat, eps)),
                    0.0) - (y - mu_hat)))
            if family == "binomial":
                mu_c = np.clip(mu_hat, eps, 1 - eps)
                return float(-2.0 * np.sum(
                    y * np.log(mu_c) + (1 - y) * np.log(1 - mu_c)))
            if family == "gamma":
                return float(2.0 * np.sum(
                    -np.log(np.maximum(y, eps) / np.maximum(mu_hat, eps))
                    + (y - mu_hat) / np.maximum(mu_hat, eps)))
            return float(np.sum((y - mu_hat) ** 2))

        dev = deviance(mu)
        # intercept-only model: mu = mean(y) for every canonical family
        null_dev = deviance(np.full_like(y, y.mean()))
        k = int(meta.get("dim", 0)) + 1
        n = len(y)
        aic = float(dev + 2 * k)
        rows = [
            ("deviance", float(dev)),
            ("nullDeviance", null_dev),
            ("aic", aic),
            ("degreesOfFreedom", float(n - k)),
        ]
        return MTable.from_rows(rows, self._out_schema(None, None))

    def _out_schema(self, *_):
        return TableSchema(["metric", "value"],
                           [AlinkTypes.STRING, AlinkTypes.DOUBLE])

"""Weak-scaling invariants on the virtual mesh: per-device compiled work
must stay ~constant as dp grows with the global batch (reference analog:
the MiniCluster-with-N-TaskManagers strategy,
test_utils/.../LocalEnvFactoryImpl.java:20-41).

These catch accidental replication/gather regressions — a batch that stops
being sharded shows up as per-device FLOPs growing with dp — which the
functional multichip dryrun cannot see."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")


def _flops(compiled) -> float:
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0]
    f = ca.get("flops", 0.0)
    assert f and np.isfinite(f), ca
    return float(f)


def _dp_values():
    n = len(jax.devices())
    return [d for d in (1, 2, 4, 8) if d <= n]


def test_lbfgs_per_device_flops_constant():
    from alink_tpu.optim import optimize, softmax_obj
    from alink_tpu.parallel.mesh import AXIS_DATA, make_mesh

    dps = _dp_values()
    assert dps[-1] >= 4, "needs the 8-virtual-device CPU mesh"
    rng = np.random.RandomState(0)
    dim, k, per_dev = 16, 3, 64
    flops = {}
    for dp in dps:
        mesh = make_mesh({AXIS_DATA: dp}, devices=jax.devices()[:dp])
        n = per_dev * dp  # weak scaling: rows grow with devices
        X = rng.rand(n, dim).astype(np.float32)
        y = rng.randint(0, k, n).astype(np.float32)
        lowered = optimize(softmax_obj(dim, k), X, y, mesh=mesh,
                           max_iter=5, _lower_only=True)
        flops[dp] = _flops(lowered.compile())
    base = flops[dps[0]]
    for dp in dps[1:]:
        ratio = flops[dp] / base
        # constant per-device work (+ small collective/overhead growth);
        # full replication would show ratio ~= dp
        assert ratio < 1.6, (flops, ratio)


def test_bert_train_step_per_device_flops_constant():
    import optax

    from alink_tpu.dl.modules import BertConfig, TransformerEncoder
    from alink_tpu.dl.sharding import (batch_sharding, make_dl_mesh,
                                       param_shardings)
    from alink_tpu.dl.train import make_train_step

    dps = _dp_values()
    assert dps[-1] >= 4
    rng = np.random.RandomState(0)
    seqlen, per_dev = 32, 2
    cfg = BertConfig(
        vocab_size=256, hidden_size=32, num_layers=2, num_heads=4,
        intermediate_size=64, max_position=seqlen, num_labels=2,
        dropout=0.0)

    def ce(logits, yy):
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, yy).mean()

    flops = {}
    for dp in dps:
        mesh = make_dl_mesh(dp=dp, tp=1, sp=1, devices=jax.devices()[:dp])
        model = TransformerEncoder(cfg)
        batch = per_dev * dp
        ids = rng.randint(0, cfg.vocab_size, (batch, seqlen)).astype(
            np.int32)
        amask = np.ones((batch, seqlen), np.int32)
        y = rng.randint(0, 2, batch).astype(np.int32)
        params = model.init(jax.random.PRNGKey(0), ids, amask)
        params = jax.device_put(params, param_shardings(params, mesh))
        tx = optax.adamw(1e-3)
        opt_state = tx.init(params["params"])
        train_step = make_train_step(model, tx, ce)
        batch_args = {
            "input_ids": jax.device_put(ids, batch_sharding(mesh, 2)),
            "attention_mask": jax.device_put(amask, batch_sharding(mesh, 2)),
        }
        y_s = jax.device_put(y, batch_sharding(mesh, 1))
        lowered = train_step.lower(params, opt_state, batch_args, y_s)
        flops[dp] = _flops(lowered.compile())
    base = flops[dps[0]]
    for dp in dps[1:]:
        ratio = flops[dp] / base
        assert ratio < 1.6, (flops, ratio)


# ---------------------------------------------------------------------------
# APS owner-routed pull/push: per-device collective bytes ~constant in M
# ---------------------------------------------------------------------------


def _aps_compiled(m, mode, routed):
    """Compile pull or push on an M-device model mesh: per-device batch B
    and rows-per-shard constant (weak scaling — the vocab grows with M)."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from alink_tpu.parallel.aps import (ShardedEmbedding, model_mesh, pull,
                                        pull_allgather, push, push_allgather)
    from alink_tpu.parallel.mesh import AXIS_MODEL
    from alink_tpu.parallel.shardmap import shard_map

    mesh = model_mesh(m)
    rows, D, B = 16, 4, 32
    V = rows * m
    table = ShardedEmbedding(mesh, V, D)
    ids = np.random.default_rng(0).integers(0, V, size=(m, B)).astype(
        np.int32)
    grads = np.ones((m, B, D), np.float32)
    if mode == "pull":
        def body(tl, i):
            return (pull if routed else pull_allgather)(
                tl, i[0], AXIS_MODEL, rows)
        spec = (P(AXIS_MODEL),) * 2
        args = (table.array, jnp.asarray(ids))
    else:
        def body(tl, i, g):
            return (push if routed else push_allgather)(
                tl, i[0], g[0], AXIS_MODEL, rows)
        spec = (P(AXIS_MODEL),) * 3
        args = (table.array, jnp.asarray(ids), jnp.asarray(grads))
    f = jax.jit(shard_map(body, mesh=mesh, in_specs=spec,
                          out_specs=P(AXIS_MODEL), check_vma=False))
    return f.lower(*args).compile()


@pytest.mark.parametrize("mode", ["pull", "push"])
def test_aps_routed_collective_bytes_constant(mode):
    """The O(B·D) claim, pinned via compiled-HLO accounting: per-device
    steady-state collective bytes stay ~flat as the model axis grows
    1→2→4→8 (M=1 compiles to zero collective traffic, so ratios are taken
    against the smallest multi-device mesh)."""
    from alink_tpu.common.profiling import collective_bytes

    ms = _dp_values()
    assert ms[-1] >= 4, "needs the 8-virtual-device CPU mesh"
    routed = {m: collective_bytes(_aps_compiled(m, mode, True)) for m in ms}
    assert routed[ms[0]] == 0 if ms[0] == 1 else routed[ms[0]] > 0
    base = routed[ms[1]]
    assert base > 0
    for m in ms[2:]:
        ratio = routed[m] / base
        # an O(M·B·D) regression (all-gathered contributions) would show
        # ratio ~= m / ms[1]
        assert ratio < 1.6, (routed, ratio)


@pytest.mark.parametrize("mode", ["pull", "push"])
def test_aps_gather_reference_collective_bytes_grow(mode):
    """Sensitivity check for the accounting itself: the legacy all-gather
    path DOES grow ~linearly in M, so a flat routed curve is signal, not a
    blind meter."""
    from alink_tpu.common.profiling import collective_bytes

    ms = [m for m in _dp_values() if m >= 2]
    if len(ms) < 2:
        pytest.skip("needs ≥4 devices")
    gathered = {m: collective_bytes(_aps_compiled(m, mode, False))
                for m in ms}
    growth = gathered[ms[-1]] / gathered[ms[0]]
    expected = ms[-1] / ms[0]
    assert growth > 0.6 * expected, (gathered, growth)
    # and routed beats gather outright on the largest mesh
    routed_big = collective_bytes(_aps_compiled(ms[-1], mode, True))
    assert routed_big < gathered[ms[-1]] / 2, (routed_big, gathered)


# ---------------------------------------------------------------------------
# the REAL huge-embedding training loop (not the micro pull/push cycle):
# per-device collective bytes ~constant in M for the routed engine, with and
# without the hot-key cache; the host (gathered) engine grows ~linearly
# ---------------------------------------------------------------------------

def _sgns_loop_bytes(m, engine, hot=0):
    """The canonical probe (shared with the BENCH `huge` extra — one
    recipe, so the CI pin and the bench measure the same program)."""
    from alink_tpu.embedding.engine import collective_bytes_probe

    return collective_bytes_probe(m, engine, hot_rows=hot)


@pytest.mark.parametrize("hot", [0, 16])
def test_sgns_training_loop_collective_bytes_flat(hot):
    """ROADMAP open item 2 at the workload level: the whole sharded-SGNS
    training program (pull → grads → push per step, hot-key cache at
    hot=16) keeps per-device steady-state collective bytes ~flat as the
    model axis grows — the micro pull/push pin alone can't see a gather
    sneaking into the composed loop."""
    ms = _dp_values()
    assert ms[-1] >= 4, "needs the 8-virtual-device CPU mesh"
    got = {m: _sgns_loop_bytes(m, "sharded", hot) for m in ms if m >= 2}
    base = got[ms[1]]
    assert base > 0
    for m in list(got)[1:]:
        ratio = got[m] / base
        assert ratio < 1.6, (got, ratio)


def test_sgns_cached_loop_bytes_below_routed():
    """The hot-key cache is a net byte reduction on the full mesh under the
    Zipf frequency table (hot pulls never ride the wire; the replica
    refresh costs a flat broadcast)."""
    ms = _dp_values()
    if ms[-1] < 4:
        pytest.skip("needs a multi-device mesh")
    m = ms[-1]
    routed = _sgns_loop_bytes(m, "sharded", hot=0)
    cached = _sgns_loop_bytes(m, "sharded", hot=16)
    assert cached < routed, (cached, routed)


def test_sgns_host_reference_bytes_grow():
    """Sensitivity check: the host engine's gathered updates DO grow
    ~linearly in M, so the flat routed curve is signal, not a blind
    meter."""
    ms = [m for m in _dp_values() if m >= 2]
    if len(ms) < 2:
        pytest.skip("needs ≥4 devices")
    got = {m: _sgns_loop_bytes(m, "host") for m in ms}
    growth = got[ms[-1]] / got[ms[0]]
    expected = ms[-1] / ms[0]
    assert growth > 0.6 * expected, (got, growth)
    # and the routed engine beats the host engine outright at full scale
    routed_big = _sgns_loop_bytes(ms[-1], "sharded")
    assert routed_big < got[ms[-1]], (routed_big, got)


def test_staged_arrays_actually_sharded():
    """Each device holds n/dp rows — full replication would hold n."""
    from alink_tpu.parallel.comqueue import shard_rows
    from alink_tpu.parallel.mesh import AXIS_DATA, make_mesh

    n_dev = len(jax.devices())
    if n_dev < 2:
        pytest.skip("needs multi-device mesh")
    mesh = make_mesh({AXIS_DATA: n_dev})
    X = np.random.RandomState(0).rand(16 * n_dev, 4).astype(np.float32)
    out = shard_rows(mesh, X)
    shard_rows_count = out.addressable_shards[0].data.shape[0]
    assert shard_rows_count == 16, (shard_rows_count, n_dev)

"""Classical classification breadth tests: NaiveBayes, KNN, FM, MLP, OneVsRest.

Mirrors the reference's operator-level integration tests (reference:
core/src/test/java/com/alibaba/alink/operator/batch/classification/
NaiveBayesTrainBatchOpTest.java, KnnTrainBatchOpTest.java,
FmClassifierTrainBatchOpTest.java, MultilayerPerceptronTrainBatchOpTest.java,
OneVsRestTrainBatchOpTest.java).
"""

import json

import numpy as np
import pytest

from alink_tpu.common import MTable
from alink_tpu.operator.base import TableSourceOp
from alink_tpu.operator.batch import (
    FmClassifierPredictBatchOp,
    FmClassifierTrainBatchOp,
    FmRegressorPredictBatchOp,
    FmRegressorTrainBatchOp,
    KnnPredictBatchOp,
    KnnTrainBatchOp,
    LogisticRegressionTrainBatchOp,
    MultilayerPerceptronPredictBatchOp,
    MultilayerPerceptronTrainBatchOp,
    NaiveBayesPredictBatchOp,
    NaiveBayesTrainBatchOp,
    OneVsRestPredictBatchOp,
    OneVsRestTrainBatchOp,
)


def _blobs(n_per=60, centers=((0, 0), (6, 6), (0, 6)), seed=0, spread=0.5):
    rng = np.random.default_rng(seed)
    X = np.concatenate(
        [rng.normal(c, spread, size=(n_per, 2)) for c in centers]
    ).astype(np.float64)
    y = np.repeat(np.arange(len(centers)), n_per)
    return X, y


def _table(X, y, label_as=str):
    return MTable({
        "f0": X[:, 0], "f1": X[:, 1],
        "label": np.asarray([label_as(v) for v in y], dtype=object),
    })


def _accuracy(out, y, pred_col="pred", label_as=str):
    pred = np.asarray(out.col(pred_col))
    truth = np.asarray([label_as(v) for v in y])
    return (pred.astype(str) == truth.astype(str)).mean()


def test_naive_bayes_gaussian():
    X, y = _blobs(centers=((1, 1), (6, 6), (1, 6)))
    src = TableSourceOp(_table(X, y))
    train = NaiveBayesTrainBatchOp(
        labelCol="label", featureCols=["f0", "f1"], modelType="GAUSSIAN"
    ).link_from(src)
    out = NaiveBayesPredictBatchOp(
        predictionCol="pred", predictionDetailCol="detail"
    ).link_from(train, src).collect()
    assert _accuracy(out, y) > 0.95
    detail = json.loads(out.col("detail")[0])
    assert set(detail) == {"0", "1", "2"}
    assert abs(sum(detail.values()) - 1.0) < 1e-6


@pytest.mark.parametrize("model_type", ["MULTINOMIAL", "BERNOULLI"])
def test_naive_bayes_count_data(model_type):
    # bag-of-words style counts: each class concentrates on 2 of 6 features
    rng = np.random.default_rng(1)
    rows, y = [], []
    for cls in range(3):
        p = np.full(6, 0.02)
        p[2 * cls:2 * cls + 2] = 0.45
        p /= p.sum()
        rows.append(rng.multinomial(20, p, size=60))
        y.extend([cls] * 60)
    X = np.concatenate(rows).astype(np.float64)
    y = np.asarray(y)
    t = MTable({f"w{j}": X[:, j] for j in range(6)}
               | {"label": np.asarray([str(v) for v in y], dtype=object)})
    src = TableSourceOp(t)
    train = NaiveBayesTrainBatchOp(
        labelCol="label", featureCols=[f"w{j}" for j in range(6)],
        modelType=model_type,
    ).link_from(src)
    out = NaiveBayesPredictBatchOp(predictionCol="pred").link_from(
        train, src
    ).collect()
    # binarizing the counts (BERNOULLI) is inherently lossier than the counts
    assert _accuracy(out, y) > (0.95 if model_type == "MULTINOMIAL" else 0.85)


def test_knn_classifier():
    X, y = _blobs()
    src = TableSourceOp(_table(X, y))
    train = KnnTrainBatchOp(labelCol="label", featureCols=["f0", "f1"]).link_from(src)
    out = KnnPredictBatchOp(k=5, predictionCol="pred").link_from(train, src).collect()
    assert _accuracy(out, y) > 0.97


def test_knn_integer_labels_and_cosine():
    X, y = _blobs(centers=((2, 0.5), (0.5, 2)))
    t = MTable({"f0": X[:, 0], "f1": X[:, 1],
                "label": np.asarray(y, dtype=np.int64)})
    src = TableSourceOp(t)
    train = KnnTrainBatchOp(
        labelCol="label", featureCols=["f0", "f1"], distanceType="COSINE"
    ).link_from(src)
    out = KnnPredictBatchOp(k=3, predictionCol="pred").link_from(train, src).collect()
    pred = np.asarray(out.col("pred"))
    assert pred.dtype.kind == "i"
    assert (pred == y).mean() > 0.9


def test_fm_classifier_nonlinear():
    # XOR-ish: linear models fail, the pairwise FM term separates it
    rng = np.random.default_rng(7)
    X = rng.uniform(-1, 1, size=(400, 2))
    y = ((X[:, 0] * X[:, 1]) > 0).astype(int)
    src = TableSourceOp(_table(X, y))
    train = FmClassifierTrainBatchOp(
        labelCol="label", featureCols=["f0", "f1"], numFactor=4, maxIter=200
    ).link_from(src)
    out = FmClassifierPredictBatchOp(
        predictionCol="pred", predictionDetailCol="detail"
    ).link_from(train, src).collect()
    assert _accuracy(out, y) > 0.9


def test_fm_regressor():
    rng = np.random.default_rng(3)
    X = rng.uniform(-1, 1, size=(300, 2))
    y = 2.0 * X[:, 0] + 3.0 * X[:, 0] * X[:, 1]
    t = MTable({"f0": X[:, 0], "f1": X[:, 1], "label": y})
    src = TableSourceOp(t)
    train = FmRegressorTrainBatchOp(
        labelCol="label", featureCols=["f0", "f1"], numFactor=4, maxIter=300
    ).link_from(src)
    out = FmRegressorPredictBatchOp(predictionCol="pred").link_from(train, src).collect()
    pred = np.asarray(out.col("pred"), np.float64)
    rmse = np.sqrt(((pred - y) ** 2).mean())
    assert rmse < 0.35


def test_mlp_classifier():
    X, y = _blobs(centers=((0, 0), (4, 4), (0, 4), (4, 0)))
    src = TableSourceOp(_table(X, y))
    train = MultilayerPerceptronTrainBatchOp(
        labelCol="label", featureCols=["f0", "f1"], layers=[16], maxIter=200
    ).link_from(src)
    out = MultilayerPerceptronPredictBatchOp(
        predictionCol="pred", predictionDetailCol="detail"
    ).link_from(train, src).collect()
    assert _accuracy(out, y) > 0.95


def test_one_vs_rest():
    X, y = _blobs()
    src = TableSourceOp(_table(X, y))
    proto = LogisticRegressionTrainBatchOp(
        labelCol="label", featureCols=["f0", "f1"], maxIter=50
    )
    train = OneVsRestTrainBatchOp(proto).link_from(src)
    out = OneVsRestPredictBatchOp(
        predictionCol="pred", predictionDetailCol="detail"
    ).link_from(train, src).collect()
    assert _accuracy(out, y) > 0.97
    detail = json.loads(out.col("detail")[0])
    assert set(detail) == {"0", "1", "2"}


def test_one_vs_rest_model_roundtrip(tmp_path):
    from alink_tpu.operator.batch import AkSinkBatchOp, AkSourceBatchOp

    X, y = _blobs(n_per=30)
    src = TableSourceOp(_table(X, y))
    proto = LogisticRegressionTrainBatchOp(
        labelCol="label", featureCols=["f0", "f1"], maxIter=30
    )
    train = OneVsRestTrainBatchOp(proto).link_from(src)
    path = str(tmp_path / "ovr.ak")
    AkSinkBatchOp(filePath=path).link_from(train).collect()
    model = AkSourceBatchOp(filePath=path)
    out = OneVsRestPredictBatchOp(predictionCol="pred").link_from(model, src).collect()
    assert _accuracy(out, y) > 0.97


def test_static_schema_no_execution():
    X, y = _blobs(n_per=10)
    src = TableSourceOp(_table(X, y))
    train = NaiveBayesTrainBatchOp(
        labelCol="label", featureCols=["f0", "f1"]
    ).link_from(src)
    pred = NaiveBayesPredictBatchOp(predictionCol="p").link_from(train, src)
    assert "p" in pred.schema.names
    assert not train._executed

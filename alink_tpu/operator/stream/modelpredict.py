"""Foreign-model predict stream ops (reference:
operator/stream/onnx/OnnxModelPredictStreamOp.java,
operator/stream/pytorch/TorchModelPredictStreamOp.java,
operator/stream/tensorflow/TFSavedModelPredictStreamOp.java).

Each micro-batch runs through the same jit-compiled ingest mapper as the
batch ops — one device launch per chunk."""

from __future__ import annotations

from ..batch.modelpredict import (
    HasIngestParams,
    OnnxModelMapper,
    StableHloModelMapper,
    TFSavedModelMapper,
    TorchModelMapper,
)
from .base import MapStreamOp


class OnnxModelPredictStreamOp(MapStreamOp, HasIngestParams):
    mapper_cls = OnnxModelMapper


class TorchModelPredictStreamOp(MapStreamOp, HasIngestParams):
    mapper_cls = TorchModelMapper


class StableHloModelPredictStreamOp(MapStreamOp, HasIngestParams):
    mapper_cls = StableHloModelMapper


class TFSavedModelPredictStreamOp(MapStreamOp, HasIngestParams):
    mapper_cls = TFSavedModelMapper
    SIGNATURE_DEF_KEY = TFSavedModelMapper.SIGNATURE_DEF_KEY

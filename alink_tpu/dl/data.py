"""Shipped real-text corpora for the BERT pretrain→finetune story.

The repo ships three small real-text artifacts (the zero-egress stand-ins
for the reference's downloadable BERT resources, BertResources.java):

- ``data/reviews_unlabeled.txt`` — 4.4k unlabeled review sentences, the
  MLM pretraining corpus;
- ``data/sst2_mini.csv`` — ~500 labeled sentiment rows (``text,label``
  with quoted commas), the fine-tune + holdout task;
- ``data/bert_tiny_sst/`` — a staged HF-layout checkpoint directory
  (config.json + model.safetensors + vocab.txt) for ingest tests.

These loaders are the one sanctioned way to read them: bench, tests and
examples all consume the same splits, so "real-text holdout accuracy"
means the same rows everywhere.
"""

from __future__ import annotations

import csv
import os
from typing import List, Optional, Tuple

import numpy as np

_DATA_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "data")


def data_path(name: str) -> str:
    """Absolute path of a shipped ``data/`` artifact."""
    return os.path.join(_DATA_DIR, name)


def load_reviews(path: Optional[str] = None,
                 limit: Optional[int] = None) -> List[str]:
    """The unlabeled review sentences (one per line, blank lines dropped)."""
    path = path or data_path("reviews_unlabeled.txt")
    with open(path, encoding="utf-8") as f:
        texts = [line.strip() for line in f]
    texts = [t for t in texts if t]
    return texts[:limit] if limit else texts


def load_sst2(path: Optional[str] = None) -> Tuple[List[str], np.ndarray]:
    """The labeled sentiment rows as ``(texts, labels)`` — csv with quoted
    commas, label in {0, 1}."""
    path = path or data_path("sst2_mini.csv")
    texts: List[str] = []
    labels: List[int] = []
    with open(path, encoding="utf-8", newline="") as f:
        for row in csv.reader(f):
            if len(row) != 2 or not row[1].strip().lstrip("-").isdigit():
                continue  # malformed line must not sink the loader
            texts.append(row[0])
            labels.append(int(row[1]))
    return texts, np.asarray(labels, np.int64)


def sst2_split(seed: int = 0, holdout: float = 0.2,
               path: Optional[str] = None):
    """Deterministic train/holdout split of the sst2 rows:
    ``(train_texts, train_y, hold_texts, hold_y)`` — the split bench and
    tests both report against."""
    texts, y = load_sst2(path)
    rng = np.random.default_rng(seed)
    perm = rng.permutation(len(texts))
    n_hold = max(1, int(len(texts) * holdout))
    hold, train = perm[:n_hold], perm[n_hold:]
    return ([texts[i] for i in train], y[train],
            [texts[i] for i in hold], y[hold])

"""IO / DL long-tail: dataset-named TFRecord ops, Xls sink, Redis/HBase
named connectors, catalog source/sink, TF table-model family, XGBoost
regression names, tensor-to-image, aggregated embedding lookup, BERT
embeddings and text-pair serving, stepwise-regression names.

Capability parity (reference: operator/batch/source/
TFRecordDatasetSourceBatchOp.java / sink/TFRecordDatasetSinkBatchOp.java;
sink/XlsSinkBatchOp.java; dataproc/LookupRedisRowBatchOp.java /
LookupRedisStringBatchOp.java / LookupHBaseBatchOp.java,
sink/RedisRowSinkBatchOp.java / RedisStringSinkBatchOp.java /
HBaseSinkBatchOp.java; source/CatalogSourceBatchOp.java /
sink/CatalogSinkBatchOp.java; dataproc/TensorFlowBatchOp.java /
TensorFlow2BatchOp.java, classification/TFTableModelClassifierPredictBatchOp
.java + regression twin + dataproc/TFTableModelPredictBatchOp.java /
TF2TableModelTrainBatchOp.java; classification/XGBoostRegTrainBatchOp.java /
XGBoostRegPredictBatchOp.java; image/WriteTensorToImageBatchOp.java;
dataproc/AggLookupBatchOp.java; classification/BertTextEmbeddingBatchOp.java
+ pair predict twins; regression/LinearRegStepwiseTrainBatchOp.java /
LinearRegStepwisePredictBatchOp.java; statistics/InternalFullStatsBatchOp
.java).
"""

from __future__ import annotations

import struct
import zlib
import numpy as np

from ...common.exceptions import (
    AkIllegalArgumentException,
    AkIllegalDataException,
)
from ...common.linalg import DenseVector, parse_vector
from ...common.mtable import AlinkTypes, MTable, TableSchema
from ...common.params import InValidator, ParamInfo
from ...io.filesystem import file_open
from ...mapper import (
    HasOutputCol,
    HasReservedCols,
    HasSelectedCol,
    ModelMapper,
)
from .base import BatchOperator
from .connectors import KvSinkBatchOp, LookupKvBatchOp
from .dl import (
    BertTextClassifierPredictBatchOp,
    BertTextModelMapper,
    BertTextPairClassifierTrainBatchOp,
    BertTextRegressorPredictBatchOp,
    BertTextRegressorTrainBatchOp,
    KerasSequentialClassifierPredictBatchOp,
    KerasSequentialClassifierTrainBatchOp,
    KerasSequentialRegressorPredictBatchOp,
    KerasSequentialRegressorTrainBatchOp,
)
from .linear import LinearRegPredictBatchOp
from .modelpredict import TFSavedModelPredictBatchOp
from .regression import StepwiseLinearRegTrainBatchOp
from .sources import TFRecordSinkBatchOp, TFRecordSourceBatchOp
from .statistics import SummarizerBatchOp
from .script import JaxScriptBatchOp
from .utils import ModelMapBatchOp
from .xgboost import XGBoostPredictBatchOp, XGBoostTrainBatchOp


# ---------------------------------------------------------------------------
# sources / sinks
# ---------------------------------------------------------------------------


class TFRecordDatasetSourceBatchOp(TFRecordSourceBatchOp):
    """(reference: operator/batch/source/TFRecordDatasetSourceBatchOp.java)"""


class TFRecordDatasetSinkBatchOp(TFRecordSinkBatchOp):
    """(reference: operator/batch/sink/TFRecordDatasetSinkBatchOp.java)"""


class XlsSinkBatchOp(BatchOperator):
    """Excel sheet sink, plugin-gated on openpyxl (reference:
    operator/batch/sink/XlsSinkBatchOp.java via connectors/connector-xls)."""

    FILE_PATH = ParamInfo("filePath", str, optional=False)
    SHEET_NAME = ParamInfo("sheetName", str, default="Sheet1")

    _min_inputs = 1
    _max_inputs = 1

    def _execute_impl(self, t: MTable) -> MTable:
        try:
            import openpyxl  # noqa: F401
        except ImportError as e:
            from ...common.exceptions import AkPluginNotExistException

            raise AkPluginNotExistException(
                "XlsSinkBatchOp needs the 'openpyxl' package (the reference "
                "ships connector-xls as a plugin): pip install openpyxl. "
                "CsvSinkBatchOp is the built-in alternative.") from e
        import pandas as pd

        df = pd.DataFrame({n: t.col(n) for n in t.names})
        with file_open(self.get(self.FILE_PATH), "wb") as f:
            df.to_excel(f, sheet_name=self.get(self.SHEET_NAME),
                        index=False)
        return t

    def _out_schema(self, in_schema):
        return in_schema


# ---------------------------------------------------------------------------
# named KV-store connectors
# ---------------------------------------------------------------------------


class LookupRedisRowBatchOp(LookupKvBatchOp):
    """Row-structured Redis lookup — field values land in output columns
    (reference: operator/batch/dataproc/LookupRedisRowBatchOp.java; the
    Redis backend resolves from the redis:// storeUri, the in-memory
    backend serves tests)."""


class LookupRedisStringBatchOp(LookupKvBatchOp):
    """Plain-string Redis lookup: the whole value lands in ONE output
    column (reference: operator/batch/dataproc/
    LookupRedisStringBatchOp.java)."""

    def _decorate(self, t: MTable, store) -> MTable:
        key_col, out_cols, _ = self._resolved_cols()
        if len(out_cols) != 1:
            raise AkIllegalArgumentException(
                "LookupRedisString writes exactly one output column")
        raw = store.mget_raw([str(v) for v in t.col(key_col)])
        kept = self._kept_input_cols(t.names)
        names = [n for n in kept if n != out_cols[0]]
        cols = {n: t.col(n) for n in names}
        types = [t.schema.type_of(n) for n in names]
        cols[out_cols[0]] = np.asarray(raw, object)
        return MTable(cols, TableSchema(names + [out_cols[0]],
                                        types + [AlinkTypes.STRING]))

    def _out_schema(self, in_schema):
        _, out_cols, _ = self._resolved_cols()
        kept = self._kept_input_cols(in_schema.names)
        names = [n for n in kept if n != out_cols[0]]
        types = [in_schema.type_of(n) for n in names]
        return TableSchema(names + [out_cols[0]],
                           types + [AlinkTypes.STRING])


class _HasHBaseParams:
    """The reference's HBase connection/table params (reference:
    params/io/HBaseConfigParams.java zookeeperQuorum/timeout +
    params/io/HBaseParams.java tableName/familyName). When these are set the
    op talks to a real HBase thrift gateway through
    :class:`alink_tpu.io.hbase.HBaseClient` (plugin-gated on happybase);
    an explicit ``storeUri`` (e.g. ``memory://`` in tests) still wins."""

    ZOOKEEPER_QUORUM = ParamInfo("zookeeperQuorum", str)
    THRIFT_HOST = ParamInfo("thriftHost", str)
    THRIFT_PORT = ParamInfo("thriftPort", int, default=9090)
    HBASE_TABLE_NAME = ParamInfo("tableName", str)
    FAMILY_NAME = ParamInfo("familyName", str, default="cf")
    TIMEOUT = ParamInfo("timeout", int, desc="thrift timeout in ms")
    # storeUri stops being required: HBase params are the primary route
    STORE_URI = ParamInfo("storeUri", str,
                          aliases=("pluginUri", "redisIp"))

    def _open_hbase_store(self):
        uri = self.get(self.STORE_URI)
        if uri:
            from ...io.kv import open_kv_store

            return open_kv_store(uri)
        from ...io.hbase import HBaseClient, HBaseKvStore

        table = self.get(self.HBASE_TABLE_NAME)
        if not table:
            raise AkIllegalArgumentException(
                "HBase ops need tableName (+ zookeeperQuorum/thriftHost), "
                "or an explicit storeUri")
        client = HBaseClient(
            thrift_host=self.get(self.THRIFT_HOST),
            thrift_port=self.get(self.THRIFT_PORT),
            zookeeper_quorum=self.get(self.ZOOKEEPER_QUORUM),
            timeout_ms=self.get(self.TIMEOUT))
        return HBaseKvStore(client=client, table=table,
                            family=self.get(self.FAMILY_NAME))


class LookupHBaseBatchOp(_HasHBaseParams, LookupKvBatchOp):
    """HBase rowkey lookup (reference: operator/batch/dataproc/
    LookupHBaseBatchOp.java). Output columns are qualifiers in
    ``familyName``; the batched thrift ``rows`` call serves each chunk."""

    def _execute_impl(self, t: MTable) -> MTable:
        store = self._open_hbase_store()
        try:
            return self._decorate(t, store)
        finally:
            store.close()


class RedisRowSinkBatchOp(KvSinkBatchOp):
    """(reference: operator/batch/sink/RedisRowSinkBatchOp.java)"""


class RedisStringSinkBatchOp(KvSinkBatchOp):
    """(reference: operator/batch/sink/RedisStringSinkBatchOp.java)"""


class HBaseSinkBatchOp(_HasHBaseParams, KvSinkBatchOp):
    """(reference: operator/batch/sink/HBaseSinkBatchOp.java — rowKeyCols
    + familyName; each selected column lands as one qualifier)."""

    ROW_KEY_COLS = ParamInfo("rowKeyCols", list, aliases=("rowKeyCol",))
    KEY_COL = ParamInfo("keyCol", str, aliases=("rowKey",))

    def _execute_impl(self, t: MTable) -> MTable:
        # reference names the key column rowKeyCols; keyCol also accepted.
        # Derived locally — executing an op must not write back params
        key = self.get(self.KEY_COL)
        if not key:
            rk = self.get(self.ROW_KEY_COLS)
            if isinstance(rk, str):  # singular alias invites a bare string
                key = rk
            elif rk:
                key = rk[0]
            else:
                raise AkIllegalArgumentException(
                    "HBaseSink needs rowKeyCols (or keyCol)")
        store = self._open_hbase_store()
        try:
            self._write(t, store, key_col=key)
        finally:
            store.close()
        return t


# ---------------------------------------------------------------------------
# catalog source / sink (sqlite catalog plays the Hive/ODPS catalog role)
# ---------------------------------------------------------------------------


class CatalogSourceBatchOp(BatchOperator):
    """Read a table registered in a database catalog (reference:
    operator/batch/source/CatalogSourceBatchOp.java — Hive/ODPS/JDBC
    catalogs). ``dbPath`` routes by scheme: ``hive://host:port/db`` opens
    the pyhive-backed HiveCatalog, ``odps://`` raises naming the missing
    driver, plain paths use the built-in JDBC-sqlite catalog
    (alink_tpu/io/hivecatalog.py)."""

    DB_PATH = ParamInfo("dbPath", str, optional=False,
                        aliases=("catalogPath", "url"))
    TABLE_NAME = ParamInfo("tableName", str, optional=False,
                           aliases=("inputTableName",))

    _max_inputs = 0

    def _execute_impl(self) -> MTable:
        from ...io.hivecatalog import open_catalog

        cat = open_catalog(self.get(self.DB_PATH))
        try:
            return cat.read_table(self.get(self.TABLE_NAME))
        finally:
            getattr(cat, "close", lambda: None)()

    def _out_schema(self):
        from ...io.hivecatalog import open_catalog

        cat = open_catalog(self.get(self.DB_PATH))
        try:
            return cat.get_table_schema(self.get(self.TABLE_NAME))
        finally:
            getattr(cat, "close", lambda: None)()


class CatalogSinkBatchOp(BatchOperator):
    """Write a table into a database catalog (reference:
    operator/batch/sink/CatalogSinkBatchOp.java). Scheme-routed like
    CatalogSourceBatchOp."""

    DB_PATH = ParamInfo("dbPath", str, optional=False,
                        aliases=("catalogPath", "url"))
    TABLE_NAME = ParamInfo("tableName", str, optional=False,
                           aliases=("outputTableName",))

    _min_inputs = 1
    _max_inputs = 1

    def _execute_impl(self, t: MTable) -> MTable:
        from ...io.hivecatalog import open_catalog

        cat = open_catalog(self.get(self.DB_PATH))
        try:
            cat.write_table(self.get(self.TABLE_NAME), t)
        finally:
            getattr(cat, "close", lambda: None)()
        return t

    def _out_schema(self, in_schema):
        return in_schema


class InternalFullStatsBatchOp(SummarizerBatchOp):
    """Full per-column statistics under the reference's internal name
    (reference: operator/batch/statistics/InternalFullStatsBatchOp.java —
    the engine behind the stats visualizer)."""


# ---------------------------------------------------------------------------
# TF table-model family (python-first collapse onto the shared DL loop)
# ---------------------------------------------------------------------------


class TFTableModelTrainBatchOp(KerasSequentialRegressorTrainBatchOp):
    """Train a user-declared network on table columns — the akdl
    TFTableModelTrain role; the reference runs a user TF script through
    DLLauncher, here the SAME layer-spec DSL trains via the shared flax
    loop and persists in the standard model table (reference:
    operator/batch/dataproc/TFTableModelTrainBatchOp.java)."""


class TF2TableModelTrainBatchOp(TFTableModelTrainBatchOp):
    """(reference: operator/batch/dataproc/TF2TableModelTrainBatchOp.java)"""


class TFTableModelRegressorPredictBatchOp(
        KerasSequentialRegressorPredictBatchOp):
    """(reference: operator/batch/regression/
    TFTableModelRegressorPredictBatchOp.java)"""


class TFTableModelClassifierPredictBatchOp(
        KerasSequentialClassifierPredictBatchOp):
    """(reference: operator/batch/classification/
    TFTableModelClassifierPredictBatchOp.java)"""


class TFTableModelClassifierTrainBatchOp(
        KerasSequentialClassifierTrainBatchOp):
    """(reference: operator/batch/classification/
    TFTableModelClassifierTrainBatchOp.java)"""


class TFTableModelRegressorTrainBatchOp(TFTableModelTrainBatchOp):
    """(reference: operator/batch/regression/
    TFTableModelRegressorTrainBatchOp.java)"""


class TFTableModelPredictBatchOp(KerasSequentialRegressorPredictBatchOp):
    """Serve a TFTableModel trainer's output on table columns — the
    (model, data) contract the rest of the family uses; foreign SavedModel
    artifacts serve through TFSavedModelPredictBatchOp instead (reference:
    operator/batch/dataproc/TFTableModelPredictBatchOp.java)."""


class TensorFlowBatchOp(JaxScriptBatchOp):
    """Run an arbitrary user training script with the session mesh + a
    dataset iterator handed in — the reference ships the table to a user
    TF1 script on a formed cluster via DLLauncher; here ``main(ctx)`` is a
    JAX script against the mesh (see JaxScriptBatchOp; the legacy ``func``
    per-table shim is kept) (reference:
    operator/batch/dataproc/TensorFlowBatchOp.java)."""


class TensorFlow2BatchOp(TensorFlowBatchOp):
    """(reference: operator/batch/tensorflow/TensorFlow2BatchOp.java)"""


# ---------------------------------------------------------------------------
# XGBoost regression names (plugin-gated like the classifier)
# ---------------------------------------------------------------------------


class XGBoostRegTrainBatchOp(XGBoostTrainBatchOp):
    """(reference: operator/batch/regression/XGBoostRegTrainBatchOp.java)"""

    def __init__(self, params=None, **kw):
        super().__init__(params, **kw)
        # default the objective to regression ONLY when unset anywhere
        # (params object or kwargs)
        if not self._params.contains("objective"):
            self._params.set("objective", "reg:squarederror")


class XGBoostRegPredictBatchOp(XGBoostPredictBatchOp):
    """(reference: operator/batch/regression/XGBoostRegPredictBatchOp.java)"""


# ---------------------------------------------------------------------------
# tensor → image (dependency-free PNG encoder)
# ---------------------------------------------------------------------------


def _png_bytes(a: np.ndarray) -> bytes:
    """Minimal PNG writer: (h, w) grayscale or (h, w, 3) RGB uint8."""
    a = np.asarray(a)
    if a.dtype != np.uint8:
        lo, hi = float(a.min()), float(a.max())
        a = ((a - lo) / (hi - lo + 1e-12) * 255).astype(np.uint8)
    if a.ndim == 2:
        color_type, channels = 0, 1
    elif a.ndim == 3 and a.shape[2] == 3:
        color_type, channels = 2, 3
    else:
        raise AkIllegalDataException(
            f"tensor shape {a.shape} is not (h, w) or (h, w, 3)")
    h, w = a.shape[:2]
    raw = b"".join(
        b"\x00" + a[i].tobytes() for i in range(h))  # filter 0 per row

    def chunk(tag: bytes, payload: bytes) -> bytes:
        return (struct.pack(">I", len(payload)) + tag + payload
                + struct.pack(">I", zlib.crc32(tag + payload)))

    ihdr = struct.pack(">IIBBBBB", w, h, 8, color_type, 0, 0, 0)
    return (b"\x89PNG\r\n\x1a\n" + chunk(b"IHDR", ihdr)
            + chunk(b"IDAT", zlib.compress(raw))
            + chunk(b"IEND", b""))


class WriteTensorToImageBatchOp(BatchOperator, HasSelectedCol,
                                HasReservedCols):
    """Write tensor cells as PNG files; the written path lands in a column
    (reference: operator/batch/image/WriteTensorToImageBatchOp.java — PNG
    encoded here by a dependency-free writer)."""

    ROOT_FILE_PATH = ParamInfo("rootFilePath", str, optional=False)
    RELATIVE_FILE_PATH_COL = ParamInfo("relativeFilePathCol", str,
                                       optional=False)

    _min_inputs = 1
    _max_inputs = 1

    def _execute_impl(self, t: MTable) -> MTable:
        root = self.get(self.ROOT_FILE_PATH).rstrip("/")
        rel_col = self.get(self.RELATIVE_FILE_PATH_COL)
        sel = self.get(HasSelectedCol.SELECTED_COL)
        for cell, rel in zip(t.col(sel), t.col(rel_col)):
            if cell is None:
                continue
            path = f"{root}/{rel}"
            with file_open(path, "wb") as f:
                f.write(_png_bytes(np.asarray(cell)))
        return t

    def _out_schema(self, in_schema):
        return in_schema


# ---------------------------------------------------------------------------
# aggregated embedding lookup
# ---------------------------------------------------------------------------


class AggLookupMapper(ModelMapper, HasSelectedCol, HasOutputCol,
                      HasReservedCols):
    """Delimited keys → aggregate of their model vectors (reference:
    operator/common/dataproc/AggLookupModelMapper.java — CONCAT/AVG/SUM/
    MAX/MIN over embedding vectors)."""

    HANDLE = ParamInfo("handle", str, default="AVG",
                       validator=InValidator("AVG", "MEAN", "SUM", "MAX",
                                             "MIN", "CONCAT"))
    DELIMITER = ParamInfo("delimiter", str, default=",")

    def load_model(self, model: MTable):
        key_col, vec_col = model.names[0], model.names[-1]
        self.lut = {str(k): parse_vector(v).to_dense().data
                    for k, v in zip(model.col(key_col), model.col(vec_col))}
        return self

    def output_schema(self, input_schema):
        out = self.get(HasOutputCol.OUTPUT_COL) or "agg_vec"
        return self._append_result_schema(
            input_schema, [out], [AlinkTypes.DENSE_VECTOR])

    def map_table(self, t: MTable) -> MTable:
        sel = self.get(HasSelectedCol.SELECTED_COL)
        how = self.get(self.HANDLE)
        delim = self.get(self.DELIMITER)
        out = self.get(HasOutputCol.OUTPUT_COL) or "agg_vec"
        vecs = np.empty(t.num_rows, object)
        for i, cell in enumerate(t.col(sel)):
            keys = ([k.strip() for k in str(cell).split(delim) if k.strip()]
                    if cell is not None else [])
            hits = [self.lut[k] for k in keys if k in self.lut]
            if not hits:
                vecs[i] = None
                continue
            M = np.stack(hits)
            if how == "CONCAT":
                vecs[i] = DenseVector(M.reshape(-1))
            elif how == "SUM":
                vecs[i] = DenseVector(M.sum(0))
            elif how == "MAX":
                vecs[i] = DenseVector(M.max(0))
            elif how == "MIN":
                vecs[i] = DenseVector(M.min(0))
            else:  # AVG / MEAN
                vecs[i] = DenseVector(M.mean(0))
        return self._append_result(
            t, {out: vecs}, {out: AlinkTypes.DENSE_VECTOR})


class AggLookupBatchOp(ModelMapBatchOp, HasSelectedCol, HasOutputCol,
                       HasReservedCols):
    """(reference: operator/batch/dataproc/AggLookupBatchOp.java)"""

    mapper_cls = AggLookupMapper
    HANDLE = AggLookupMapper.HANDLE
    DELIMITER = AggLookupMapper.DELIMITER


# ---------------------------------------------------------------------------
# BERT embedding + text-pair serving names
# ---------------------------------------------------------------------------


class BertTextEmbeddingMapper(BertTextModelMapper):
    """Pooled encoder output as the embedding vector (reference:
    operator/batch/classification/BertTextEmbeddingBatchOp.java — the
    reference embeds with a pretrained checkpoint; here any model trained
    by the BertText trainers serves, pre-head pooled states)."""

    def output_schema(self, input_schema):
        return self._append_result_schema(
            input_schema, ["embedding"], [AlinkTypes.DENSE_VECTOR])

    def map_table(self, t: MTable) -> MTable:
        import jax

        meta = self.meta
        text_col = self.get(self.TEXT_COL) or meta["textCol"]
        texts = [str(v) for v in t.col(text_col)]
        enc = self.tokenizer.encode_batch(
            texts, None, max_len=int(meta["maxSeqLength"]))
        pooled = np.asarray(jax.device_get(self.model.apply(
            self.params, **{k: np.asarray(v) for k, v in enc.items()},
            return_pooled=True)))
        out = "embedding"
        vecs = np.empty(t.num_rows, object)
        for i in range(t.num_rows):
            vecs[i] = DenseVector(pooled[i].astype(np.float64))
        return self._append_result(
            t, {out: vecs}, {out: AlinkTypes.DENSE_VECTOR})


class BertTextEmbeddingBatchOp(ModelMapBatchOp, HasReservedCols):
    """(reference: operator/batch/classification/
    BertTextEmbeddingBatchOp.java)"""

    mapper_cls = BertTextEmbeddingMapper


class BertTextPairClassifierPredictBatchOp(BertTextClassifierPredictBatchOp):
    """(reference: operator/batch/classification/
    BertTextPairClassifierPredictBatchOp.java — the shared mapper reads
    textPairCol from the model meta)."""


class BertTextPairRegressorTrainBatchOp(BertTextRegressorTrainBatchOp):
    """(reference: operator/batch/regression/
    BertTextPairRegressorTrainBatchOp.java)"""

    TEXT_PAIR_COL = BertTextPairClassifierTrainBatchOp.TEXT_PAIR_COL


class BertTextPairRegressorPredictBatchOp(BertTextRegressorPredictBatchOp):
    """(reference: operator/batch/regression/
    BertTextPairRegressorPredictBatchOp.java)"""


# ---------------------------------------------------------------------------
# stepwise-regression reference names
# ---------------------------------------------------------------------------


class LinearRegStepwiseTrainBatchOp(StepwiseLinearRegTrainBatchOp):
    """(reference: operator/batch/regression/
    LinearRegStepwiseTrainBatchOp.java)"""


class LinearRegStepwisePredictBatchOp(LinearRegPredictBatchOp):
    """(reference: operator/batch/regression/
    LinearRegStepwisePredictBatchOp.java — the stepwise model serves
    through the standard linear predictor)."""

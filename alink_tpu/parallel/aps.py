"""APS analog: model-axis sharded embedding tables with O(B·D) pull/push.

Capability parity with the reference's Alink Parameter Server (reference:
core/src/main/java/com/alibaba/alink/operator/common/aps/ApsEnv.java:39-370 —
mini-batch pull→train→push with the model partitioned by key across tasks;
ApsFuncIndex4Pull / ApsFuncTrain / ApsFuncUpdateModel; used by
operator/batch/huge/impl/Word2VecImpl.java:82-91 and the DeepWalk/Node2Vec/
MetaPath2Vec embedding family).

TPU-first re-design: there are no PS processes. The embedding table is a
``jax.Array`` row-sharded over the ``model`` mesh axis (each device owns
V/M contiguous rows — the APS key partition). Inside ``shard_map``, pull and
push route ids to the shard that OWNS them, so per-device wire bytes stay
~``slack·B·D`` no matter how many shards the table spans (the reference's
point-to-point pull/push RPCs, expressed as fixed-shape ``all_to_all``):

- **pull(ids)**: dedup the id batch, bucket unique ids by owning shard into
  fixed-capacity buckets of ``ceil(slack·B/M)`` rows, one ``all_to_all``
  (ids out), a local gather on each owner, one ``all_to_all`` back (rows
  home). This is the reference's ApsFuncIndex4Pull/pull RPC.
- **push(ids, grads)**: bucket (id, grad) rows by owner — ids ride the same
  ``all_to_all`` payload bitcast into a trailing lane — then each owner
  scatter-adds exactly the updates for its rows. Only the touched (B, D)
  grads move; the table itself never rides a collective.
- **Overflow**: the installed JAX has no ragged ``all_to_all``, so buckets
  are fixed-capacity. Ids past capacity (a pathologically skewed batch) are
  counted in the ``aps.bucket_overflows`` metric and served by the legacy
  all-gather path (:func:`pull_allgather`/:func:`push_allgather`) — inside
  a mesh-agreed ``lax.cond`` so the steady state never pays for it. Pull
  patches up the overflow remainder only; push re-applies the whole batch
  from the pre-push table (a remainder patch-up would split a duplicated
  row's contributions across two scatters and reassociate the float adds).
  Capacity slack is the ``ALINK_APS_BUCKET_SLACK`` knob (default 2.0).

Both routed paths are bit-identical to the all-gather reference: pull is
pure data movement, and push pre-combines duplicates with the identical
dedup computation and replays the reference's source-device scatter-add
order on each owner.

Memory per device is V/M rows — vocabularies larger than one chip's HBM
train fine, which is the whole point of the reference's "huge" family.
"""

from __future__ import annotations

import math
from typing import Callable, Optional

import numpy as np

from .mesh import AXIS_MODEL, default_mesh, make_mesh, pad_to_multiple
from .shardmap import axis_size


def model_mesh(n_devices: Optional[int] = None):
    """1-D mesh over the ``model`` axis — APS workers are both data and
    model holders (reference: ApsEnv runs pull/train/push on the same tasks)."""
    import jax

    devices = jax.devices() if n_devices is None else jax.devices()[:n_devices]
    return make_mesh([(AXIS_MODEL, len(devices))], devices)


def shard_table(mesh, table: np.ndarray, axis: str = AXIS_MODEL):
    """Place (V, D) onto the mesh row-sharded over ``axis``; pads V to a
    multiple of the axis size. Returns (sharded_array, padded_rows)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    m = mesh.shape[axis]
    v_pad = pad_to_multiple(table.shape[0], m)
    if v_pad != table.shape[0]:
        table = np.concatenate(
            [table, np.zeros((v_pad - table.shape[0],) + table.shape[1:],
                             table.dtype)])
    return jax.device_put(table, NamedSharding(mesh, P(axis))), v_pad


def bucket_slack(override: Optional[float] = None) -> float:
    """Bucket over-provisioning factor (``ALINK_APS_BUCKET_SLACK``, ≥ 1)."""
    if override is not None:
        return max(1.0, float(override))
    from ..common.env import env_float

    return max(1.0, env_float("ALINK_APS_BUCKET_SLACK", 2.0))


def bucket_capacity(batch: int, num_shards: int,
                    slack: Optional[float] = None) -> int:
    """Fixed per-owner bucket capacity: ``ceil(slack·B/M)`` rows."""
    return max(1, int(math.ceil(bucket_slack(slack) * batch / num_shards)))


def _note_overflow(n, dev) -> None:
    # fires only when the fallback branch actually executes; count once per
    # step (device 0 speaks for the psum-agreed total)
    if int(dev) == 0:
        from ..common.metrics import metrics

        metrics.incr("aps.bucket_overflows", int(n))


def _bucket_positions(owner_c):
    """Per-element arrival rank within its owner bucket, preserving batch
    order (stable) so routed scatter-adds replay the legacy accumulation
    order."""
    import jax.numpy as jnp

    n = owner_c.shape[0]
    order = jnp.argsort(owner_c)                    # jax sorts are stable
    sorted_owner = owner_c[order]
    pos_sorted = jnp.arange(n, dtype=jnp.int32) - jnp.searchsorted(
        sorted_owner, sorted_owner).astype(jnp.int32)
    return jnp.zeros(n, jnp.int32).at[order].set(pos_sorted)


def pull_allgather(table_l, ids, axis: str, rows_per_shard: int):
    """Legacy O(M·B·D) pull: ``all_gather`` every device's ids + masked
    local gather + ``psum``. Kept as the bit-exactness reference and as the
    bucket-overflow fallback path."""
    import jax
    import jax.numpy as jnp

    m = jax.lax.axis_index(axis)
    ids_all = jax.lax.all_gather(ids, axis)               # (M, B)
    local_idx = jnp.clip(ids_all - m * rows_per_shard, 0, rows_per_shard - 1)
    owned = (ids_all // rows_per_shard) == m              # (M, B)
    contrib = table_l[local_idx] * owned[..., None]       # (M, B, D)
    full = jax.lax.psum(contrib, axis)                    # (M, B, D)
    return jax.lax.dynamic_index_in_dim(full, m, axis=0, keepdims=False)


def _dedup_batch(ids, grads, fill):
    """Per-device dedup: combine duplicate ids' grads onto the (sorted)
    unique id list. Both push paths run this identical computation, so the
    duplicate-combination bits agree between them by construction."""
    import jax.numpy as jnp

    b = ids.shape[0]
    uid, inv = jnp.unique(ids, return_inverse=True, size=b,
                          fill_value=jnp.int32(fill))
    g = jnp.zeros((b,) + grads.shape[1:], grads.dtype).at[inv].add(grads)
    return uid, g


def _push_gathered(table_l, uid, grads, axis: str, rows_per_shard: int,
                   scale: float):
    """all_gather + local scatter-add of an already-deduped batch."""
    import jax
    import jax.numpy as jnp

    m = jax.lax.axis_index(axis)
    ids_all = jax.lax.all_gather(uid, axis).reshape(-1)          # (M*B,)
    grads_all = jax.lax.all_gather(grads, axis)                  # (M, B, D)
    grads_all = grads_all.reshape(-1, grads.shape[-1])
    local_idx = ids_all - m * rows_per_shard
    owned = (local_idx >= 0) & (local_idx < rows_per_shard)
    # foreign rows are parked at the OOB index and dropped, so each owned
    # row's scatter-add reduction group holds exactly its true
    # contributions in source-device order — masked-zero updates would
    # perturb XLA's reduction grouping at the ulp level
    lidx = jnp.where(owned, local_idx, rows_per_shard)
    return table_l.at[lidx].add(-scale * grads_all, mode="drop")


def push_allgather(table_l, ids, grads, axis: str, rows_per_shard: int,
                   scale: float = 1.0):
    """Legacy O(M·B·D) push: per-device dedup, then ``all_gather`` of
    (ids, grads) + masked local scatter-add. Reference/fallback twin of
    :func:`push`."""
    M = axis_size(axis)
    uid, g = _dedup_batch(ids, grads, M * rows_per_shard)
    return _push_gathered(table_l, uid, g, axis, rows_per_shard, scale)


def apply_gathered_replicated(table, ids, grads, axis: str, num_rows: int,
                              scale):
    """Replicated-table twin of :func:`push` — the "host engine" update.

    Per-device dedup, then ``all_gather`` of (uid, grads) + a full-table
    scatter-add applied identically on every device. Each row's scatter-add
    reduction group holds exactly its true contributions in source-device
    order — the same per-row add sequence the routed/all-gather sharded
    pushes replay — so a replicated table driven through this function
    evolves bit-identically to a model-sharded one driven through
    :func:`push` on an equal-size mesh. That is the parity contract the
    huge-embedding engines (``ALINK_HUGE_ENGINE=sharded|host``) are pinned
    against. Ids outside ``[0, num_rows)`` (dedup padding) park at the OOB
    row and drop."""
    import jax
    import jax.numpy as jnp

    uid, g = _dedup_batch(ids, grads, num_rows)
    ids_all = jax.lax.all_gather(uid, axis).reshape(-1)
    g_all = jax.lax.all_gather(g, axis).reshape(-1, g.shape[-1])
    lidx = jnp.where((ids_all >= 0) & (ids_all < num_rows), ids_all, num_rows)
    return table.at[lidx].add(-scale * g_all, mode="drop")


def aps_summary() -> dict:
    """One-call health readout of the APS exchange + hot-key cache counters
    (the block the WebUI profile panel and bench read)."""
    from ..common.metrics import metrics

    hits = metrics.counter("aps.cache_hits")
    misses = metrics.counter("aps.cache_misses")
    return {
        "cache_hits": hits,
        "cache_misses": misses,
        "cache_evictions": metrics.counter("aps.cache_evictions"),
        "cache_hit_rate": round(hits / (hits + misses), 4)
        if hits + misses else None,
        "bucket_overflows": metrics.counter("aps.bucket_overflows"),
    }


def _export_aps_gauges() -> None:
    # labeled gauges alongside the raw *_total counters: one family per
    # surface (cache events / exchange health), refreshed at scrape time
    from ..common.metrics import metrics

    for event in ("hits", "misses", "evictions"):
        metrics.set_gauge("aps.cache_events",
                          metrics.counter(f"aps.cache_{event}"), event=event)
    metrics.set_gauge("aps.health", metrics.counter("aps.bucket_overflows"),
                      event="bucket_overflows")


def _register_gauges() -> None:
    from ..common.metrics import metrics

    metrics.register_export_hook(_export_aps_gauges)


_register_gauges()


def pull(table_l, ids, axis: str, rows_per_shard: int, *,
         slack: Optional[float] = None, cap: Optional[int] = None):
    """Inside shard_map: fetch rows for this device's ``ids`` from whichever
    shard owns them. ``table_l``: (V/M, D) local shard; ``ids``: (B,) global
    row ids. Returns (B, D).

    Owner-routed: per-device comm is ~``slack·B·D`` regardless of the model
    axis size (see module docstring); ids whose bucket overflows fall back
    to :func:`pull_allgather` under a mesh-agreed ``cond``. ``cap`` overrides
    the per-owner bucket capacity (the hot-key cache sizes the cold
    remainder's buckets from the empirical tail mass — see
    ``parallel/hotcache.py``); out-of-range ids (e.g. the cache's parked
    sentinel ``M·rows``) are dropped and read back as zero rows.
    """
    import jax
    import jax.numpy as jnp

    M = axis_size(axis)
    B = int(ids.shape[0])
    rows = rows_per_shard
    cap = bucket_capacity(B, M, slack) if cap is None else max(1, int(cap))
    m = jax.lax.axis_index(axis)
    ids = ids.astype(jnp.int32)

    # dedup: a batch usually touches far fewer unique rows than B
    uid, inv = jnp.unique(ids, return_inverse=True, size=B,
                          fill_value=jnp.int32(M * rows))
    owner = uid // rows
    valid = (owner >= 0) & (owner < M)
    owner_c = jnp.where(valid, owner, M)        # parked at OOB row M → drop
    pos = _bucket_positions(owner_c)
    in_bucket = valid & (pos < cap)
    ovf = valid & (pos >= cap)

    send = jnp.zeros((M, cap), jnp.int32).at[owner_c, pos].set(
        uid, mode="drop")
    recv = jax.lax.all_to_all(send, axis, 0, 0, tiled=True)   # ids asked of me
    served = table_l[jnp.clip(recv - m * rows, 0, rows - 1)]  # (M, cap, D)
    home = jax.lax.all_to_all(served, axis, 0, 0, tiled=True)
    vals = home[jnp.clip(owner_c, 0, M - 1), jnp.clip(pos, 0, cap - 1)]
    vals = jnp.where(in_bucket[:, None], vals, jnp.zeros_like(vals))

    if cap >= B:            # overflow statically impossible
        return vals[inv]

    n_ovf = jax.lax.psum(ovf.sum(), axis)

    def _fallback(_):
        jax.debug.callback(_note_overflow, n_ovf, m)
        return pull_allgather(table_l, ids, axis, rows)

    fb = jax.lax.cond(
        n_ovf > 0, _fallback,
        lambda _: jnp.zeros((B,) + table_l.shape[1:], table_l.dtype), None)
    return jnp.where(ovf[inv][:, None], fb, vals[inv])


def push(table_l, ids, grads, axis: str, rows_per_shard: int,
         scale: float = 1.0, *, slack: Optional[float] = None):
    """Inside shard_map: apply ``-scale * grads`` for ``ids`` to the owning
    shards — per-device dedup, then owner-routed (combined grads ride one
    ``all_to_all`` with their id bitcast into a trailing lane; each owner
    scatter-adds its rows).

    Bit-identical to :func:`push_allgather`: duplicates are pre-combined by
    the same dedup computation, and routed rows land on each owner in
    source-device order, replaying the reference's scatter-add accumulation
    order. On bucket overflow the fallback ``cond`` re-applies the WHOLE
    batch from the pre-push table via the all-gather path (discarding the
    routed result) — a remainder-only patch-up would interleave a
    duplicated row's contributions across two scatters and break
    bit-exactness. Steady state never takes that branch.
    """
    import jax
    import jax.numpy as jnp

    M = axis_size(axis)
    B = int(ids.shape[0])
    D = int(grads.shape[-1])
    rows = rows_per_shard
    cap = bucket_capacity(B, M, slack)
    m = jax.lax.axis_index(axis)
    ids = ids.astype(jnp.int32)

    uid, g = _dedup_batch(ids, grads, M * rows)
    owner = uid // rows
    valid = (owner >= 0) & (owner < M)
    owner_c = jnp.where(valid, owner, M)
    pos = _bucket_positions(owner_c)
    ovf = valid & (pos >= cap)

    # bucket padding carries id M·rows (owned by nobody → dropped on the
    # receiving side) and zero grads
    send_ids = jnp.full((M, cap), jnp.int32(M * rows)).at[owner_c, pos].set(
        uid, mode="drop")
    send_g = jnp.zeros((M, cap, D), g.dtype).at[owner_c, pos].set(
        g, mode="drop")
    if g.dtype == jnp.float32:
        payload = jnp.concatenate(
            [send_g,
             jax.lax.bitcast_convert_type(send_ids, jnp.float32)[..., None]],
            axis=-1)
        rec = jax.lax.all_to_all(payload, axis, 0, 0, tiled=True)
        rg = rec[..., :D].reshape(M * cap, D)
        rid = jax.lax.bitcast_convert_type(
            rec[..., D], jnp.int32).reshape(M * cap)
    else:                   # non-32-bit grads: ids ride their own collective
        rid = jax.lax.all_to_all(
            send_ids, axis, 0, 0, tiled=True).reshape(M * cap)
        rg = jax.lax.all_to_all(
            send_g, axis, 0, 0, tiled=True).reshape(M * cap, D)

    local = rid - m * rows
    owned = (local >= 0) & (local < rows)
    # same OOB-park-and-drop trick as _push_gathered: a row's reduction
    # group must contain exactly its true contributions, in the same order
    routed = table_l.at[jnp.where(owned, local, rows)].add(
        -scale * rg, mode="drop")

    if cap >= B:            # overflow statically impossible
        return routed

    n_ovf = jax.lax.psum(ovf.sum(), axis)

    def _fallback(args):
        t0, _ = args
        jax.debug.callback(_note_overflow, n_ovf, m)
        return _push_gathered(t0, uid, g, axis, rows, scale)

    return jax.lax.cond(n_ovf > 0, _fallback, lambda args: args[1],
                        (table_l, routed))


class ShardedEmbedding:
    """Host-side handle for a model-sharded (V, D) table.

    The table lives device-resident between training calls (the reference
    keeps the APS model in task memory across iteration blocks,
    ApsEnv.java:198-327); ``to_numpy()`` is the final persist
    (persistentModel:328)."""

    def __init__(self, mesh, vocab_size: int, dim: int,
                 init: Optional[Callable[[np.random.Generator], np.ndarray]] = None,
                 seed: int = 0, axis: str = AXIS_MODEL):
        self.mesh = mesh
        self.axis = axis
        self.vocab_size = vocab_size
        self.dim = dim
        rng = np.random.default_rng(seed)
        table = (init(rng) if init is not None
                 else ((rng.random((vocab_size, dim)) - 0.5) / dim)
                 .astype(np.float32))
        self.array, self.padded_rows = shard_table(mesh, table, axis)
        self.rows_per_shard = self.padded_rows // mesh.shape[axis]

    def to_numpy(self) -> np.ndarray:
        import jax

        return np.asarray(jax.device_get(self.array))[:self.vocab_size]

    def shard_shapes(self):
        return [tuple(s.data.shape) for s in self.array.addressable_shards]

    def save(self, path: str):
        """Persist the table as a .ak model file (the APS persistentModel
        analog, reference: ApsEnv.java:328-366)."""
        from ..common.model import model_to_table
        from ..io.ak import write_ak

        meta = {"modelName": "ShardedEmbedding",
                "vocabSize": self.vocab_size, "dim": self.dim}
        write_ak(path, model_to_table(meta, {"table": self.to_numpy()}))

    @staticmethod
    def load(mesh, path: str, axis: str = AXIS_MODEL) -> "ShardedEmbedding":
        """Restore a saved table back onto the mesh, re-sharded."""
        from ..common.model import table_to_model
        from ..io.ak import read_ak

        meta, arrays = table_to_model(read_ak(path))
        handle = ShardedEmbedding(mesh, meta["vocabSize"], meta["dim"],
                                  init=lambda rng: arrays["table"]
                                  .astype(np.float32), axis=axis)
        return handle

"""HBase client + Hive catalog adapters: contract round trips against
protocol doubles, and honest plugin raises without drivers.

(reference: common/io/hbase/HBase.java, connectors/connector-hbase/,
common/io/catalog/HiveCatalog.java, OdpsCatalog.java)
"""

import numpy as np
import pytest

import alink_tpu.io.hbase as hb
from alink_tpu.common.exceptions import AkPluginNotExistException
from alink_tpu.common.mtable import AlinkTypes, MTable
from alink_tpu.io.hbase import HBaseClient, HBaseKvStore
from alink_tpu.io.hivecatalog import HiveCatalog, open_catalog


# -- happybase protocol double ----------------------------------------------


class FakeTable:
    def __init__(self):
        self.data = {}  # rowkey bytes -> {b"cf:qual": bytes}

    def put(self, row, cells):
        self.data.setdefault(row, {}).update(cells)

    def _filter(self, cells, columns):
        if not columns:
            return dict(cells)
        out = {}
        for k, v in cells.items():
            for c in columns:
                fam = c if b":" not in c else None
                if (fam and k.split(b":")[0] == fam) or k == c:
                    out[k] = v
        return out

    def row(self, row, columns=None):
        return self._filter(self.data.get(row, {}), columns)

    def rows(self, rowkeys, columns=None):
        return [(rk, self._filter(self.data[rk], columns))
                for rk in rowkeys if rk in self.data]


class FakeConnection:
    def __init__(self):
        self.tables = {}
        self.closed = False

    def create_table(self, name, families):
        self.tables[name] = FakeTable()

    def table(self, name):
        return self.tables.setdefault(name, FakeTable())

    def close(self):
        self.closed = True


def test_hbase_client_contract_roundtrip():
    conn = FakeConnection()
    c = HBaseClient(connection=conn)
    c.create_table("t", "cf", "meta")
    c.set("t", "r1", "cf", {"a": b"1", "b": b"x"})
    c.set("t", "r1", "meta", {"ts": b"9"})
    c.set("t", "r2", "cf", {"a": b"2"})

    assert c.get_column("t", "r1", "cf", "a") == b"1"
    assert c.get_column("t", "r1", "cf", "missing") is None
    assert c.get_family_columns("t", "r1", "cf") == {"a": b"1", "b": b"x"}
    assert c.get_row("t", "r1") == {"cf": {"a": b"1", "b": b"x"},
                                    "meta": {"ts": b"9"}}
    # batched get preserves order, misses are empty
    rows = c.get_rows("t", ["r2", "nope", "r1"], "cf")
    assert rows == [{"a": b"2"}, {}, {"a": b"1", "b": b"x"}]
    c.close()
    assert conn.closed


def test_hbase_kv_store_json_values():
    store = HBaseKvStore(client=HBaseClient(connection=FakeConnection()),
                         table="t", family="cf")
    store.set("k1", {"price": 3.5, "name": "ab"})
    assert store.get("k1") == {"price": 3.5, "name": "ab"}
    assert store.mget(["k1", "gone"]) == [{"price": 3.5, "name": "ab"}, None]


def test_hbase_ops_end_to_end(monkeypatch):
    """Sink rows into the (fake-thrift) cluster, look them back up through
    LookupHBaseBatchOp — the full op path with reference param names."""
    from alink_tpu.operator.batch import (HBaseSinkBatchOp,
                                          LookupHBaseBatchOp)
    from alink_tpu.operator.batch.base import TableSourceBatchOp

    shared = FakeConnection()
    monkeypatch.setattr(hb, "connection_factory",
                        lambda host, port, timeout: shared)

    items = MTable({"sku": np.asarray(["a", "b", "c"], object),
                    "price": np.asarray([1.5, 2.5, 3.5]),
                    "stock": np.asarray([10, 0, 7], np.int64)})
    HBaseSinkBatchOp(
        tableName="items", familyName="f", rowKeyCols=["sku"],
        zookeeperQuorum="zk1:2181,zk2:2181",
    ).link_from(TableSourceBatchOp(items)).collect()

    q = MTable({"sku": np.asarray(["b", "zz", "a"], object)})
    out = LookupHBaseBatchOp(
        tableName="items", familyName="f", thriftHost="zk1",
        selectedCols=["sku"], outputCols=["price", "stock"],
        outputTypes=["DOUBLE", "DOUBLE"],
    ).link_from(TableSourceBatchOp(q)).collect()
    price = np.asarray(out.col("price"))
    assert price[0] == 2.5 and np.isnan(price[1]) and price[2] == 1.5
    assert out.schema.type_of("stock") == AlinkTypes.DOUBLE


def test_hbase_stream_twins_take_reference_params(monkeypatch):
    from alink_tpu.operator.stream import (HBaseSinkStreamOp,
                                           LookupHBaseStreamOp)
    from alink_tpu.operator.stream.base import TableSourceStreamOp

    shared = FakeConnection()
    monkeypatch.setattr(hb, "connection_factory",
                        lambda host, port, timeout: shared)
    items = MTable({"k": np.asarray(["a", "b", "c", "d"], object),
                    "v": np.asarray([1.0, 2.0, 3.0, 4.0])})
    HBaseSinkStreamOp(
        tableName="st", familyName="f", rowKeyCols=["k"], thriftHost="h",
    ).link_from(TableSourceStreamOp(items, chunkSize=2)).collect()
    out = LookupHBaseStreamOp(
        tableName="st", familyName="f", thriftHost="h",
        selectedCols=["k"], outputCols=["v"], outputTypes=["DOUBLE"],
    ).link_from(TableSourceStreamOp(
        MTable({"k": np.asarray(["d", "a"], object)}), chunkSize=1)).collect()
    assert list(np.asarray(out.col("v"))) == [4.0, 1.0]


def test_hbase_without_driver_raises(monkeypatch):
    monkeypatch.setattr(hb, "connection_factory", None)
    with pytest.raises(AkPluginNotExistException, match="happybase"):
        HBaseClient(thrift_host="h")


# -- Hive catalog (DB-API double) -------------------------------------------


class FakeCursor:
    def __init__(self, owner):
        self.owner = owner
        self._result = []

    def execute(self, sql, params=None):
        self.owner.log.append((sql, params))
        s = sql.strip()
        up = s.upper()
        if up == "SHOW TABLES":
            self._result = [(n,) for n in self.owner.tables]
        elif up.startswith("DESCRIBE"):
            name = s.split("`")[1]
            self._result = self.owner.tables[name]["schema"]
        elif up.startswith("SELECT"):
            name = s.split("`")[1]
            self._result = self.owner.tables[name]["rows"]
        elif up.startswith("CREATE TABLE"):
            name = s.split("`")[1]
            cols = []
            inner = s[s.index("(") + 1: s.rindex(")")]
            for piece in inner.split(","):
                cn, ct = piece.strip().split()
                cols.append((cn.strip("`"), ct.lower()))
            self.owner.tables.setdefault(name, {"schema": cols, "rows": []})
        elif up.startswith("INSERT INTO"):
            name = s.split("`")[1]
            width = len(self.owner.tables[name]["schema"])
            vals = list(params)
            rows = [tuple(vals[i:i + width])
                    for i in range(0, len(vals), width)]
            self.owner.tables[name]["rows"].extend(rows)

    def fetchall(self):
        return self._result


class FakeHiveConn:
    def __init__(self):
        self.tables = {}
        self.log = []

    def cursor(self):
        return FakeCursor(self)


def test_hive_catalog_adapter_shape():
    conn = FakeHiveConn()
    conn.tables["sales"] = {
        "schema": [("region", "string"), ("amount", "double"),
                   ("qty", "bigint"), ("# Partition Information", "")],
        "rows": [("east", 10.5, 3), ("west", None, 4)],
    }
    cat = HiveCatalog(connection=conn)
    assert cat.list_tables() == ["sales"]
    schema = cat.get_table_schema("sales")
    assert schema.names == ["region", "amount", "qty"]
    assert schema.types == [AlinkTypes.STRING, AlinkTypes.DOUBLE,
                            AlinkTypes.LONG]
    t = cat.read_table("sales")
    assert t.num_rows == 2
    amounts = np.asarray(t.col("amount"))
    assert amounts[0] == 10.5 and np.isnan(amounts[1])

    # write path: CREATE + one multi-row INSERT
    out = MTable({"k": np.asarray(["a", "b"], object),
                  "v": np.asarray([1.0, 2.0])})
    cat.write_table("copied", out)
    assert cat.read_table("copied").num_rows == 2
    sqls = [s for s, _ in conn.log]
    assert any(s.startswith("CREATE TABLE IF NOT EXISTS `copied`")
               for s in sqls)
    assert sum(s.startswith("INSERT INTO `copied`") for s in sqls) == 1


def test_catalog_routing(tmp_path):
    # plain path -> sqlite catalog (the built-in)
    from alink_tpu.operator.sqlengine import SqliteCatalog

    cat = open_catalog(str(tmp_path / "c.db"))
    assert isinstance(cat, SqliteCatalog)
    # odps:// / datahub:// -> honest raises naming the driver
    with pytest.raises(AkPluginNotExistException, match="pyodps"):
        open_catalog("odps://project/table")
    with pytest.raises(AkPluginNotExistException, match="pydatahub"):
        open_catalog("datahub://project/topic")
    # hive:// without pyhive -> honest raise naming the driver
    with pytest.raises(AkPluginNotExistException, match="pyhive"):
        open_catalog("hive://h:10000/db")
    # hive:// with an injected connection parses host/port/db
    c = HiveCatalog.from_url("hive://h:7000/mydb",
                             connection=FakeHiveConn())
    assert c.database == "mydb"


def test_catalog_ops_on_sqlite(tmp_path):
    from alink_tpu.operator.batch import (CatalogSinkBatchOp,
                                          CatalogSourceBatchOp)
    from alink_tpu.operator.batch.base import TableSourceBatchOp

    db = str(tmp_path / "cat.db")
    t = MTable({"a": np.asarray([1.0, 2.0]), "b": np.asarray([3, 4],
                                                            np.int64)})
    CatalogSinkBatchOp(dbPath=db, tableName="t1").link_from(
        TableSourceBatchOp(t)).collect()
    back = CatalogSourceBatchOp(dbPath=db, tableName="t1").collect()
    assert back.num_rows == 2
    np.testing.assert_allclose(np.asarray(back.col("a")), [1.0, 2.0])

"""Recommendation long-tail: ALS variants (implicit / MF / hot-point),
similar-users serving, UserCf/ItemCf cross-role kernels, vec-dot models,
negative sampling, ranking lists, recommendation re-ranking.

Capability parity (reference: operator/batch/recommendation/
AlsImplicitTrainBatchOp.java, MfAlsBatchOp.java / MfAlsForHotPointBatchOp
.java, AlsForHotPointTrainBatchOp.java, AlsImplicitForHotPointTrainBatchOp
.java, AlsSimilarUsersRecommBatchOp.java, UserCfItemsPerUserRecommBatchOp
.java / UserCfUsersPerItemRecommBatchOp.java / UserCfSimilarUsersRecomm
BatchOp.java, ItemCfUsersPerItemRecommBatchOp.java,
FmRecommBinaryImplicitTrainBatchOp.java, NegativeItemSamplingBatchOp.java,
VecDotModelGeneratorBatchOp.java / VecDotItemsPerUserRecommBatchOp.java,
RankingListBatchOp.java, RecommendationRankingBatchOp.java,
SwingRecommBatchOp.java).
"""

from __future__ import annotations

import json
from typing import Dict, List

import numpy as np

from ...common.exceptions import (
    AkIllegalArgumentException,
    AkIllegalDataException,
)
from ...common.linalg import parse_vector
from ...common.model import model_to_table, table_to_model
from ...common.mtable import AlinkTypes, MTable, TableSchema
from ...common.params import MinValidator, ParamInfo
from ...mapper import HasPredictionCol, HasReservedCols
from .base import BatchOperator
from .recommendation import (
    AlsItemsPerUserRecommMapper,
    AlsSimilarItemsRecommBatchOp,
    AlsTrainBatchOp,
    FmRecommTrainBatchOp,
    HasRecommTripleCols,
    SwingSimilarItemsRecommBatchOp,
    _AlsTopKMapper,
    _CfRecommMapper,
    _RecommOpBase,
    _SimilarItemsMapper,
    _recomm_json,
)
from .utils import ModelTrainOpMixin


# ---------------------------------------------------------------------------
# ALS trainer variants
# ---------------------------------------------------------------------------


class AlsImplicitTrainBatchOp(AlsTrainBatchOp):
    """ALS with implicit preferences preset (Hu/Koren/Volinsky)
    (reference: recommendation/AlsImplicitTrainBatchOp.java)."""

    def __init__(self, params=None, **kw):
        kw.setdefault("implicitPrefs", True)
        super().__init__(params, **kw)


class _HotPointMixin:
    """Cap per-entity neighbor lists: the padded-rectangle sweep is sized by
    the max degree, so one viral entity inflates every row — subsample hub
    histories (reference: the ForHotPoint family's dedicated hub path).
    Hooks into the base trainer; the sweep itself is unchanged."""

    MAX_NEIGHBOR_NUMBER = ParamInfo(
        "maxNeighborNumber", int, default=512, validator=MinValidator(1),
        desc="cap on ratings per user/item fed to each sweep")

    def _max_neighbors(self) -> int:
        return self.get(self.MAX_NEIGHBOR_NUMBER)

    def _extra_meta(self) -> dict:
        return {"maxNeighborNumber": self.get(self.MAX_NEIGHBOR_NUMBER)}


class AlsForHotPointTrainBatchOp(_HotPointMixin, AlsTrainBatchOp):
    """(reference: recommendation/AlsForHotPointTrainBatchOp.java)"""


class AlsImplicitForHotPointTrainBatchOp(_HotPointMixin,
                                         AlsImplicitTrainBatchOp):
    """(reference: recommendation/AlsImplicitForHotPointTrainBatchOp.java)"""


class MfAlsBatchOp(AlsTrainBatchOp):
    """Matrix-factorization-by-ALS under its mf-family name
    (reference: operator/batch/recommendation/MfAlsBatchOp.java)."""


class MfAlsForHotPointBatchOp(_HotPointMixin, AlsTrainBatchOp):
    """(reference: operator/batch/recommendation/MfAlsForHotPointBatchOp.java)"""


class FmRecommBinaryImplicitTrainBatchOp(FmRecommTrainBatchOp):
    """FM recommender on binary implicit feedback: observed triples with a
    positive rate become label 1, non-positive rates label 0 (so an
    impression-without-click column trains as an explicit negative); without
    a rate column every triple is a positive (reference: recommendation/
    FmRecommBinaryImplicitTrainBatchOp.java)."""

    def _execute_impl(self, t: MTable) -> MTable:
        rate_col = self.get(self.RATE_COL)
        if rate_col:
            binary = t.with_column(
                rate_col,
                (np.asarray(t.col(rate_col), np.float64) > 0
                 ).astype(np.float64),
                AlinkTypes.DOUBLE)
        else:
            binary = t
        return super()._execute_impl(binary)


# ---------------------------------------------------------------------------
# ALS similar-users serving
# ---------------------------------------------------------------------------


class AlsSimilarUsersRecommMapper(_AlsTopKMapper):
    """Top-K nearest users by user-factor COSINE similarity — the same
    normalization the similar-items kernel uses, so hub users with large
    factor norms don't dominate every list; cosine also guarantees the
    query user ranks itself first, making self-exclusion exact (reference:
    recommendation/AlsSimilarUsersRecommBatchOp.java)."""

    def map_table(self, t: MTable) -> MTable:
        col = self.get(self.USER_COL) or self.meta["userCol"]
        k = min(self.get(self.K) + 1, len(self.user_ids))
        q = self._lookup(t.col(col), self.u_index)
        valid = q >= 0
        norms = np.linalg.norm(self.U, axis=1, keepdims=True)
        Un = self.U / np.maximum(norms, 1e-12)
        Q = Un[np.maximum(q, 0)]
        scores, idx = self._topk_jit(Un, Q, k)
        scores, idx = np.asarray(scores), np.asarray(idx)
        rows = []
        for r in range(t.num_rows):
            if not valid[r]:
                rows.append(_recomm_json(np.empty(0), np.empty(0), False))
                continue
            keep = idx[r] != q[r]  # drop the query user itself
            ids = self.user_ids[idx[r][keep]][: self.get(self.K)]
            sc = scores[r][keep][: self.get(self.K)]
            rows.append(_recomm_json(ids, sc, True))
        oc = self._out_col()
        return self._append_result(
            t, {oc: np.asarray(rows, object)}, {oc: AlinkTypes.STRING})


class AlsSimilarUsersRecommBatchOp(_RecommOpBase):
    mapper_cls = AlsSimilarUsersRecommMapper


# ---------------------------------------------------------------------------
# CF cross-role serving kernels
# ---------------------------------------------------------------------------


class UserCfItemsPerUserRecommMapper(_CfRecommMapper):
    """UserCf top-K items for a user: score(i) = Σ_{v∈sim(u)} sim(u,v)·r_vi
    (reference: UserCfRecommKernel.recommendItemsPerUser)."""

    def output_schema(self, input_schema: TableSchema) -> TableSchema:
        return self._append_result_schema(
            input_schema, [self._out_col()], [AlinkTypes.STRING])

    def map_table(self, t: MTable) -> MTable:
        ucol = self.get(self.USER_COL) or self.meta["userCol"]
        k = self.get(self.K)
        rows = []
        for uv in t.col(ucol):
            u = self.u_index.get(uv, -1)
            if u < 0:
                rows.append(_recomm_json(np.empty(0), np.empty(0), False))
                continue
            scores = np.zeros(len(self.item_ids), np.float32)
            # neighbors of u in BOTH directions of the top-K lists
            sims = dict(self.sim_of[u])
            for v, s in self.rev.get(u, []):
                sims.setdefault(v, s)
            for v, s in sims.items():
                for i, rate in self.hist.get(v, []):
                    scores[i] += s * rate
            seen = [i for i, _ in self.hist.get(u, [])]
            scores[seen] = -np.inf
            top = np.argsort(-scores)[:k]
            top = top[np.isfinite(scores[top]) & (scores[top] > 0)]
            rows.append(_recomm_json(self.item_ids[top], scores[top], True))
        oc = self._out_col()
        return self._append_result(
            t, {oc: np.asarray(rows, object)}, {oc: AlinkTypes.STRING})


class UserCfUsersPerItemRecommMapper(_CfRecommMapper):
    """UserCf top-K users for an item: score(v) = Σ_{v'∈U_i} sim(v,v')·r_v'i
    (reference: UserCfRecommKernel.recommendUsersPerItem)."""

    def output_schema(self, input_schema: TableSchema) -> TableSchema:
        return self._append_result_schema(
            input_schema, [self._out_col()], [AlinkTypes.STRING])

    def map_table(self, t: MTable) -> MTable:
        icol = self.get(self.ITEM_COL) or self.meta["itemCol"]
        k = self.get(self.K)
        rows = []
        for iv in t.col(icol):
            i = self.i_index.get(iv, -1)
            if i < 0:
                rows.append(_recomm_json(np.empty(0), np.empty(0), False))
                continue
            scores = np.zeros(len(self.user_ids), np.float32)
            raters = self.hist_by_item.get(i, [])
            for v2, rate in raters:
                sims = dict(self.sim_of[v2])
                for v, s in self.rev.get(v2, []):
                    sims.setdefault(v, s)
                for v, s in sims.items():
                    scores[v] += s * rate
            scores[[v for v, _ in raters]] = -np.inf
            top = np.argsort(-scores)[:k]
            top = top[np.isfinite(scores[top]) & (scores[top] > 0)]
            rows.append(_recomm_json(self.user_ids[top], scores[top], True))
        oc = self._out_col()
        return self._append_result(
            t, {oc: np.asarray(rows, object)}, {oc: AlinkTypes.STRING})


class _SimilarUsersMapper(_SimilarItemsMapper):
    """Top-K similar USERS from a kind=user CF model — same neighbor lists,
    queried by the user column."""

    def map_table(self, t: MTable) -> MTable:
        col = self.get(self.USER_COL) or self.meta["userCol"]
        k = self.get(self.K)
        rows = []
        for v in t.col(col):
            e = self.e_index.get(v, -1)
            if e < 0:
                rows.append(_recomm_json(np.empty(0), np.empty(0), False))
                continue
            nb, sm = self.nbrs[e][:k], self.sims[e][:k]
            keep = sm > 0
            rows.append(
                _recomm_json(self.entity_ids[nb[keep]], sm[keep], True))
        oc = self._out_col()
        return self._append_result(
            t, {oc: np.asarray(rows, object)}, {oc: AlinkTypes.STRING})


class UserCfItemsPerUserRecommBatchOp(_RecommOpBase):
    mapper_cls = UserCfItemsPerUserRecommMapper


class UserCfUsersPerItemRecommBatchOp(_RecommOpBase):
    mapper_cls = UserCfUsersPerItemRecommMapper


class UserCfSimilarUsersRecommBatchOp(_RecommOpBase):
    mapper_cls = _SimilarUsersMapper


class ItemCfUsersPerItemRecommMapper(_CfRecommMapper):
    """ItemCf top-K users for an item: score(v) = Σ_{j∈I_v} sim(i,j)·r_vj
    (reference: ItemCfRecommKernel.recommendUsersPerItem)."""

    def output_schema(self, input_schema: TableSchema) -> TableSchema:
        return self._append_result_schema(
            input_schema, [self._out_col()], [AlinkTypes.STRING])

    def map_table(self, t: MTable) -> MTable:
        icol = self.get(self.ITEM_COL) or self.meta["itemCol"]
        k = self.get(self.K)
        rows = []
        for iv in t.col(icol):
            i = self.i_index.get(iv, -1)
            if i < 0:
                rows.append(_recomm_json(np.empty(0), np.empty(0), False))
                continue
            sims = dict(self.sim_of[i])
            for j, s in self.rev.get(i, []):
                sims.setdefault(j, s)
            scores = np.zeros(len(self.user_ids), np.float32)
            for j, s in sims.items():
                for v, rate in self.hist_by_item.get(j, []):
                    scores[v] += s * rate
            raters = [v for v, _ in self.hist_by_item.get(i, [])]
            scores[raters] = -np.inf
            top = np.argsort(-scores)[:k]
            top = top[np.isfinite(scores[top]) & (scores[top] > 0)]
            rows.append(_recomm_json(self.user_ids[top], scores[top], True))
        oc = self._out_col()
        return self._append_result(
            t, {oc: np.asarray(rows, object)}, {oc: AlinkTypes.STRING})


class ItemCfUsersPerItemRecommBatchOp(_RecommOpBase):
    mapper_cls = ItemCfUsersPerItemRecommMapper


class SwingRecommBatchOp(SwingSimilarItemsRecommBatchOp):
    """(reference: recommendation/SwingRecommBatchOp.java — swing serves
    similar-items only)."""


# ---------------------------------------------------------------------------
# vec-dot model: user/item embedding tables → ALS-format model
# ---------------------------------------------------------------------------


class VecDotModelGeneratorBatchOp(ModelTrainOpMixin, BatchOperator):
    """Build a dot-product recommender model from precomputed (id, vector)
    tables — users first input, items second; the output is an AlsModel, so
    EVERY ALS serving kernel works on it (reference: recommendation/
    VecDotModelGeneratorBatchOp.java)."""

    USER_ID_COL = ParamInfo("userIdCol", str, default=None)
    USER_VEC_COL = ParamInfo("userVecCol", str, default=None)
    ITEM_ID_COL = ParamInfo("itemIdCol", str, default=None)
    ITEM_VEC_COL = ParamInfo("itemVecCol", str, default=None)

    _min_inputs = 2
    _max_inputs = 2

    def _static_meta_keys(self, in_schema):
        return {"modelName": "AlsModel"}

    @staticmethod
    def _id_vec(t: MTable, id_col, vec_col):
        id_col = id_col or t.names[0]
        vec_col = vec_col or t.names[1]
        ids = np.asarray(t.col(id_col))
        vecs = np.stack([parse_vector(v).to_dense().data
                         for v in t.col(vec_col)]).astype(np.float32)
        return id_col, ids, vecs

    def _execute_impl(self, users: MTable, items: MTable) -> MTable:
        ucol, uid, uvec = self._id_vec(users, self.get(self.USER_ID_COL),
                                       self.get(self.USER_VEC_COL))
        icol, iid, ivec = self._id_vec(items, self.get(self.ITEM_ID_COL),
                                       self.get(self.ITEM_VEC_COL))
        if uvec.shape[1] != ivec.shape[1]:
            raise AkIllegalDataException(
                f"user/item vector dims differ: {uvec.shape[1]} vs "
                f"{ivec.shape[1]}")
        meta = {"modelName": "AlsModel", "userCol": ucol, "itemCol": icol,
                "rateCol": None, "rank": int(uvec.shape[1]),
                "implicitPrefs": False, "source": "vecDot"}
        return model_to_table(meta, {
            "userIds": uid, "itemIds": iid,
            "userFactors": uvec, "itemFactors": ivec,
        })


class VecDotItemsPerUserRecommBatchOp(_RecommOpBase):
    """Top-K items by user·item dot product over the vec-dot model —
    identical serving math to ALS items-per-user (reference:
    recommendation/VecDotItemsPerUserRecommBatchOp.java)."""

    mapper_cls = AlsItemsPerUserRecommMapper


# ---------------------------------------------------------------------------
# negative sampling / ranking list / recommendation re-ranking
# ---------------------------------------------------------------------------


class NegativeItemSamplingBatchOp(BatchOperator):
    """(user, item) positives → labeled table with k random unseen-item
    negatives per positive; like the reference, the first two columns are
    (user, item) unless named explicitly (reference: recommendation/
    NegativeItemSamplingBatchOp.java)."""

    USER_COL = ParamInfo("userCol", str, default=None)
    ITEM_COL = ParamInfo("itemCol", str, default=None)
    SAMPLING_FACTOR = ParamInfo("samplingFactor", int, default=3,
                                validator=MinValidator(1))
    SEED = ParamInfo("randomSeed", int, default=0, aliases=("seed",))

    _min_inputs = 1
    _max_inputs = 1

    def _execute_impl(self, t: MTable) -> MTable:
        ucol = self.get(self.USER_COL) or t.names[0]
        icol = self.get(self.ITEM_COL) or t.names[1]
        users = np.asarray(t.col(ucol))
        items = np.asarray(t.col(icol))
        item_ids = np.unique(items)
        seen = {}
        for u, i in zip(users, items):
            seen.setdefault(u, set()).add(i)
        rng = np.random.default_rng(self.get(self.SEED))
        k = self.get(self.SAMPLING_FACTOR)
        out_u, out_i, out_y = [], [], []
        for u, i in zip(users, items):
            out_u.append(u)
            out_i.append(i)
            out_y.append(1)
            drawn = 0
            tries = 0
            while drawn < k and tries < 20 * k:
                cand = item_ids[rng.integers(len(item_ids))]
                tries += 1
                if cand not in seen[u]:
                    out_u.append(u)
                    out_i.append(cand)
                    out_y.append(0)
                    drawn += 1
        return MTable.from_rows(
            list(zip(out_u, out_i, out_y)),
            TableSchema([ucol, icol, "label"],
                        [t.schema.type_of(ucol), t.schema.type_of(icol),
                         AlinkTypes.LONG]))

    def _out_schema(self, in_schema):
        ucol = self.get(self.USER_COL) or in_schema.names[0]
        icol = self.get(self.ITEM_COL) or in_schema.names[1]
        return TableSchema(
            [ucol, icol, "label"],
            [in_schema.type_of(ucol), in_schema.type_of(icol),
             AlinkTypes.LONG])


class RankingListBatchOp(BatchOperator):
    """Top-N ranking list: count/sum objects (optionally per group) and rank
    (reference: operator/batch/recommendation/RankingListBatchOp.java)."""

    OBJECT_COL = ParamInfo("objectCol", str, optional=False)
    GROUP_COL = ParamInfo("groupCol", str, default=None)
    SCORE_COL = ParamInfo("scoreCol", str, default=None,
                          desc="sum this column; default counts rows")
    TOP_N = ParamInfo("topN", int, default=10, validator=MinValidator(1))

    _min_inputs = 1
    _max_inputs = 1

    def _execute_impl(self, t: MTable) -> MTable:
        import pandas as pd

        obj = self.get(self.OBJECT_COL)
        grp = self.get(self.GROUP_COL)
        score = self.get(self.SCORE_COL)
        n = self.get(self.TOP_N)
        df = pd.DataFrame({c: t.col(c) for c in t.names})
        keys = ([grp] if grp else []) + [obj]
        agg = (df.groupby(keys, dropna=False)[score].sum() if score
               else df.groupby(keys, dropna=False).size())
        agg = agg.reset_index(name="score")
        if grp:
            agg["rank"] = agg.groupby(grp)["score"].rank(
                ascending=False, method="first").astype(np.int64)
            agg = agg[agg["rank"] <= n].sort_values([grp, "rank"])
        else:
            agg = agg.sort_values("score", ascending=False).head(n)
            agg["rank"] = np.arange(1, len(agg) + 1, dtype=np.int64)
        cols = ([grp] if grp else []) + [obj, "rank", "score"]
        agg = agg[cols]
        types = (([t.schema.type_of(grp)] if grp else [])
                 + [t.schema.type_of(obj), AlinkTypes.LONG,
                    AlinkTypes.DOUBLE])
        return MTable(
            {c: agg[c].to_numpy() for c in cols},
            TableSchema(cols, types))

    def _out_schema(self, in_schema):
        obj = self.get(self.OBJECT_COL)
        grp = self.get(self.GROUP_COL)
        cols = ([grp] if grp else []) + [obj, "rank", "score"]
        types = (([in_schema.type_of(grp)] if grp else [])
                 + [in_schema.type_of(obj), AlinkTypes.LONG,
                    AlinkTypes.DOUBLE])
        return TableSchema(cols, types)


class RecommendationRankingBatchOp(BatchOperator):
    """Re-rank a recommendation column with a trained pipeline model: each
    candidate joins its row's features, the model scores the pairs, and the
    top-N by score replace the original list (reference: recommendation/
    RecommendationRankingBatchOp.java — PipelineModel rescoring).

    Inputs: (pipeline model table, data). The recomm column holds the
    ``{"object": [...], "rate": [...]}`` JSON the serving kernels emit."""

    RECOMM_COL = ParamInfo("mTableCol", str, optional=False,
                           aliases=("recommCol",))
    OBJECT_COL_NAME = ParamInfo("objectColName", str, default="object",
                                desc="candidate column name fed to the model")
    PREDICTION_SCORE_COL = ParamInfo("predictionScoreCol", str,
                                     default="pred",
                                     desc="model output column to rank by")
    TOP_N = ParamInfo("topN", int, default=10, validator=MinValidator(1))
    OUTPUT_COL = ParamInfo("outputCol", str, default=None)

    _min_inputs = 2
    _max_inputs = 2

    def _execute_impl(self, model_t: MTable, t: MTable) -> MTable:
        from ...pipeline.pipeline import PipelineModel

        pipe = PipelineModel.from_table(model_t)
        rcol = self.get(self.RECOMM_COL)
        obj_col = self.get(self.OBJECT_COL_NAME)
        score_col = self.get(self.PREDICTION_SCORE_COL)
        out_col = self.get(self.OUTPUT_COL) or rcol
        top_n = self.get(self.TOP_N)

        feature_cols = [c for c in t.names if c != rcol]
        feat_arrays = [t.col(c) for c in feature_cols]
        rec_cells = t.col(rcol)
        cand_rows = []
        owners = []
        for i in range(t.num_rows):
            cell = rec_cells[i]
            obj = json.loads(str(cell)) if cell is not None else {}
            base = tuple(a[i] for a in feat_arrays)
            for o in obj.get("object", []):
                cand_rows.append(base + (o,))
                owners.append(i)
        empty = _recomm_json(np.empty(0), np.empty(0), False)
        if not cand_rows:
            # no candidates anywhere: still emit the promised output column
            ranked = np.full(t.num_rows, empty, object)
            return t.with_column(out_col, ranked, AlinkTypes.STRING)
        cand = MTable.from_rows(
            cand_rows,
            TableSchema(feature_cols + [obj_col],
                        [t.schema.type_of(c) for c in feature_cols]
                        + [AlinkTypes.STRING]))
        from .base import TableSourceBatchOp

        scored = pipe.transform(TableSourceBatchOp(cand)).collect()
        if score_col not in scored.names:
            raise AkIllegalArgumentException(
                f"ranking model emitted no {score_col!r} column "
                f"(have {scored.names})")
        scores = np.asarray(scored.col(score_col), np.float64)
        # rank the ORIGINAL candidate ids — pipeline stages (StringIndexer
        # etc.) may have rewritten the object column in place
        objs_arr = np.asarray([r[-1] for r in cand_rows], object)
        owners = np.asarray(owners)
        ranked = np.full(t.num_rows, empty, object)
        # one group-by over the candidate table instead of a per-row scan
        order = np.argsort(owners, kind="stable")
        bounds = np.searchsorted(owners[order],
                                 np.arange(t.num_rows + 1))
        for i in range(t.num_rows):
            grp = order[bounds[i]:bounds[i + 1]]
            if grp.size == 0:
                continue
            s = scores[grp]
            pick = grp[np.argsort(-s)[:top_n]]
            ranked[i] = _recomm_json(objs_arr[pick], scores[pick], True)
        return t.with_column(out_col, ranked, AlinkTypes.STRING)

    def _out_schema(self, model_schema, in_schema):
        out_col = self.get(self.OUTPUT_COL) or self.get(self.RECOMM_COL)
        if out_col in in_schema.names:
            return in_schema
        return TableSchema(list(in_schema.names) + [out_col],
                           list(in_schema.types) + [AlinkTypes.STRING])

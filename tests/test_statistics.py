"""Statistics ops + probabilistic distributions.

Mirrors the reference's statistics test style (reference:
core/src/test/java/com/alibaba/alink/operator/batch/statistics/
CorrelationBatchOpTest.java, ChiSquareTestBatchOpTest.java): tiny in-memory
datasets, assert numeric outputs.
"""

import math

import numpy as np
import pytest

from alink_tpu.operator.batch import (
    ChiSquareTestBatchOp,
    CorrelationBatchOp,
    CovarianceBatchOp,
    MemSourceBatchOp,
    QuantileBatchOp,
    SummarizerBatchOp,
    VectorChiSquareTestBatchOp,
    VectorCorrelationBatchOp,
    VectorSummarizerBatchOp,
)
from alink_tpu.stats.prob import CDF, IDF, PDF, XRandom


def _xy_source(n=200, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=n)
    y = 2.0 * x + rng.normal(scale=0.1, size=n)
    z = rng.normal(size=n)
    rows = [(float(a), float(b), float(c)) for a, b, c in zip(x, y, z)]
    return MemSourceBatchOp(rows, "x double, y double, z double")


def test_pearson_correlation():
    corr = CorrelationBatchOp().link_from(_xy_source()).collect_correlation()
    m = corr.correlation_matrix
    assert corr.col_names == ["x", "y", "z"]
    assert m[0, 0] == pytest.approx(1.0)
    assert m[0, 1] == pytest.approx(1.0, abs=0.01)
    assert abs(m[0, 2]) < 0.25


def test_spearman_correlation_monotone_invariance():
    rng = np.random.default_rng(1)
    x = rng.normal(size=100)
    rows = [(float(a), float(math.exp(a))) for a in x]
    src = MemSourceBatchOp(rows, "x double, ex double")
    m = (CorrelationBatchOp(method="SPEARMAN").link_from(src)
         .collect_correlation().correlation_matrix)
    assert m[0, 1] == pytest.approx(1.0)


def test_vector_correlation():
    rng = np.random.default_rng(2)
    rows = [(f"{a} {-a}",) for a in rng.normal(size=50)]
    src = MemSourceBatchOp(rows, "vec string")
    m = (VectorCorrelationBatchOp(selectedCol="vec").link_from(src)
         .collect_correlation().correlation_matrix)
    assert m[0, 1] == pytest.approx(-1.0)


def test_chi_square_dependence():
    # col 'dep' is a deterministic function of the label; 'ind' is independent
    rng = np.random.default_rng(3)
    rows = []
    for _ in range(300):
        label = int(rng.integers(2))
        rows.append((("a" if label else "b"), str(rng.integers(2)), label))
    src = MemSourceBatchOp(rows, "dep string, ind string, label int")
    out = (ChiSquareTestBatchOp(selectedCols=["dep", "ind"], labelCol="label")
           .link_from(src).collect())
    by_col = {r[0]: r for r in out.rows()}
    assert by_col["dep"][2] < 1e-6       # p-value ~ 0
    assert by_col["ind"][2] > 0.01


def test_vector_chi_square():
    rows = [(f"{i % 2} {1 - i % 2}", i % 2) for i in range(100)]
    src = MemSourceBatchOp(rows, "vec string, label int")
    out = (VectorChiSquareTestBatchOp(selectedCol="vec", labelCol="label")
           .link_from(src).collect())
    assert all(r[2] < 1e-6 for r in out.rows())


def test_quantile_op():
    rows = [(float(i),) for i in range(101)]
    out = (QuantileBatchOp(selectedCols=["v"], quantileNum=4)
           .link_from(MemSourceBatchOp(rows, "v double")).collect())
    assert list(out.col("v")) == [0.0, 25.0, 50.0, 75.0, 100.0]


def test_summarizer_and_covariance():
    src = _xy_source()
    s = SummarizerBatchOp().link_from(src).collect_summary()
    assert s.count("x") == 200
    assert s.mean("x") == pytest.approx(0.0, abs=0.2)
    cov = CovarianceBatchOp().link_from(src).collect()
    # var(y) ≈ 4*var(x)
    names = list(cov.col("colName"))
    vx = cov.col("x")[names.index("x")]
    vy = cov.col("y")[names.index("y")]
    assert vy / vx == pytest.approx(4.0, rel=0.15)


def test_vector_summarizer():
    rows = [(f"{i} {2 * i}",) for i in range(10)]
    src = MemSourceBatchOp(rows, "vec string")
    s = (VectorSummarizerBatchOp(selectedCol="vec").link_from(src)
         .collect_vector_summary())
    assert s.mean("v0") == pytest.approx(4.5)
    assert s.mean("v1") == pytest.approx(9.0)


# -- probabilistic module (reference: common/probabilistic/CDF.java etc.) ---

def test_normal_cdf_idf_roundtrip():
    p = CDF.normal(1.96)
    assert p == pytest.approx(0.975, abs=1e-4)
    assert IDF.normal(p) == pytest.approx(1.96, abs=1e-6)


def test_chi2_known_values():
    # chi2 cdf with df=2 is 1 - exp(-x/2)
    for x in (0.5, 1.0, 3.0, 10.0):
        assert CDF.chi2(x, 2) == pytest.approx(1 - math.exp(-x / 2), abs=1e-10)
    assert IDF.chi2(0.95, 2) == pytest.approx(-2 * math.log(0.05), abs=1e-6)


def test_student_t_f_symmetry():
    assert CDF.student_t(0.0, 7) == pytest.approx(0.5)
    assert CDF.student_t(-2.0, 7) == pytest.approx(1 - CDF.student_t(2.0, 7))
    # F(1, d2->inf) ~ chi2(1)
    assert CDF.f(3.84, 1, 100000) == pytest.approx(CDF.chi2(3.84, 1), abs=1e-3)


def test_pdf_integrates():
    xs = np.linspace(-8, 8, 4001)
    for pdf in (lambda x: PDF.normal(x),
                lambda x: PDF.student_t(x, 5)):
        total = np.trapezoid(pdf(xs), xs)
        assert total == pytest.approx(1.0, abs=1e-3)


def test_xrandom_matches_cdf():
    r = XRandom(seed=42)
    draws = r.normal(size=20000)
    emp = (draws < 1.0).mean()
    assert emp == pytest.approx(CDF.normal(1.0), abs=0.01)

"""Stream twins of the non-mapper NLP batch ops (per-micro-batch corpus).

Capability parity (reference: operator/stream/nlp/
KeywordsExtractionStreamOp.java, DocWordCountStreamOp.java — each
micro-batch is the corpus window)."""

from __future__ import annotations

from typing import List

__all__: List[str] = []


def _generate():
    from ..batch import nlp as batch_nlp
    from .base import make_per_chunk_twin

    for batch_name, name in (
        ("KeywordsExtractionBatchOp", "KeywordsExtractionStreamOp"),
        ("DocWordCountBatchOp", "DocWordCountStreamOp"),
    ):
        cls = getattr(batch_nlp, batch_name)
        doc = (f"Stream twin of {batch_name}: each micro-batch is the "
               f"corpus window (reference: operator/stream/nlp/{name}.java).")
        globals()[name] = make_per_chunk_twin(cls, name, doc)
        __all__.append(name)


_generate()

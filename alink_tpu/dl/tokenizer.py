"""WordPiece-style tokenizer with corpus-built vocab.

The reference ships pretrained BERT vocabularies through its resource-plugin
downloader (reference: core/src/main/java/com/alibaba/alink/common/dl/
BertResources.java:28,76-85). This build runs in a zero-egress environment, so
the tokenizer can (a) load a local vocab file with the standard BERT format,
or (b) build a frequency vocab from the training corpus — greedy
longest-match-first WordPiece with ``##`` continuation, same algorithm family
as the reference's BERT tokenization.
"""

from __future__ import annotations

import collections
import re
import unicodedata
from typing import Dict, List, Optional, Sequence

import numpy as np

PAD, UNK, CLS, SEP, MASK = "[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"
_SPECIALS = [PAD, UNK, CLS, SEP, MASK]


def _is_punctuation(ch: str) -> bool:
    cp = ord(ch)
    # ASCII non-alnum blocks count as punctuation (BERT convention, so that
    # e.g. "$" and "`" split even though unicodedata calls them symbols)
    if (33 <= cp <= 47 or 58 <= cp <= 64 or 91 <= cp <= 96 or
            123 <= cp <= 126):
        return True
    return unicodedata.category(ch).startswith("P")


def _is_cjk(cp: int) -> bool:
    return (0x4E00 <= cp <= 0x9FFF or 0x3400 <= cp <= 0x4DBF or
            0x20000 <= cp <= 0x2A6DF or 0x2A700 <= cp <= 0x2B73F or
            0x2B740 <= cp <= 0x2B81F or 0x2B820 <= cp <= 0x2CEAF or
            0xF900 <= cp <= 0xFAFF or 0x2F800 <= cp <= 0x2FA1F)


def _basic_tokens(text: str, do_lower_case: bool = True) -> List[str]:
    """BERT basic tokenization: clean control chars, isolate CJK chars,
    optionally lowercase + strip accents, split on punctuation."""
    if do_lower_case:
        text = text.lower()
        text = "".join(ch for ch in unicodedata.normalize("NFD", text)
                       if unicodedata.category(ch) != "Mn")
    out: List[str] = []
    word: List[str] = []

    def flush():
        if word:
            out.append("".join(word))
            word.clear()

    for ch in text:
        # whitespace first: \t \n \r are category Cc but BERT treats them
        # as word separators, not strippable control chars
        if ch.isspace():
            flush()
            continue
        cp = ord(ch)
        if cp == 0 or cp == 0xFFFD or unicodedata.category(ch).startswith("C"):
            continue
        if _is_cjk(cp) or _is_punctuation(ch):
            flush()
            out.append(ch)
        else:
            word.append(ch)
    flush()
    return out


_LEGACY_TOKEN_RE = re.compile(r"\w+|[^\w\s]", re.UNICODE)


class Tokenizer:
    def __init__(self, vocab: Dict[str, int], max_input_chars_per_word: int = 64,
                 do_lower_case: bool = True, legacy: bool = False):
        self.vocab = vocab
        self.inv = {i: t for t, i in vocab.items()}
        self.max_chars = max_input_chars_per_word
        self.do_lower_case = do_lower_case
        # pre-round-4 models built their vocab with a \w+ regex (no accent
        # stripping, "_" kept inside words); serving them must keep that
        # behavior or their vocab entries stop matching
        self.legacy = legacy

    # -- construction ------------------------------------------------------
    @staticmethod
    def from_vocab_file(path: str, do_lower_case: bool = True) -> "Tokenizer":
        vocab = {}
        with open(path, encoding="utf-8") as f:
            for i, line in enumerate(f):
                vocab[line.rstrip("\n")] = i
        return Tokenizer(vocab, do_lower_case=do_lower_case)

    @staticmethod
    def build(texts: Sequence[str], vocab_size: int = 8000) -> "Tokenizer":
        """Frequency vocab: whole words + single chars as fallback pieces."""
        counter: collections.Counter = collections.Counter()
        chars: collections.Counter = collections.Counter()
        for t in texts:
            for w in _basic_tokens(t):
                counter[w] += 1
                chars.update(w)
        vocab = {s: i for i, s in enumerate(_SPECIALS)}
        for ch, _ in chars.most_common():
            if len(vocab) >= vocab_size:
                break
            if ch not in vocab:
                vocab[ch] = len(vocab)
            cont = "##" + ch
            if len(vocab) < vocab_size and cont not in vocab:
                vocab[cont] = len(vocab)
        for w, _ in counter.most_common():
            if len(vocab) >= vocab_size:
                break
            if w not in vocab:
                vocab[w] = len(vocab)
        return Tokenizer(vocab)

    # -- encoding ----------------------------------------------------------
    def _wordpiece(self, word: str) -> List[str]:
        if len(word) > self.max_chars:
            return [UNK]
        pieces, start = [], 0
        while start < len(word):
            end = len(word)
            cur = None
            while start < end:
                sub = word[start:end]
                if start > 0:
                    sub = "##" + sub
                if sub in self.vocab:
                    cur = sub
                    break
                end -= 1
            if cur is None:
                return [UNK]
            pieces.append(cur)
            start = end
        return pieces

    def tokenize(self, text: str) -> List[str]:
        words = (_LEGACY_TOKEN_RE.findall(text.lower()) if self.legacy
                 else _basic_tokens(text, self.do_lower_case))
        out = []
        for w in words:
            out.extend(self._wordpiece(w))
        return out

    def encode(
        self,
        text: str,
        pair: Optional[str] = None,
        max_len: int = 128,
    ):
        """Returns (input_ids, attention_mask, token_type_ids), BERT layout:
        [CLS] a... [SEP] b... [SEP], padded to max_len."""
        a = self.tokenize(text)
        b = self.tokenize(pair) if pair is not None else []
        budget = max_len - 2 - (1 if b else 0)
        if b:
            # longest-first truncation keeps both segments represented
            while len(a) + len(b) > budget:
                (a if len(a) >= len(b) else b).pop()
        else:
            a = a[:budget]
        toks = [CLS] + a + [SEP] + (b + [SEP] if b else [])
        types = [0] * (len(a) + 2) + [1] * (len(b) + 1 if b else 0)
        ids = [self.vocab.get(t, self.vocab[UNK]) for t in toks]
        mask = [1] * len(ids)
        pad = max_len - len(ids)
        ids += [self.vocab[PAD]] * pad
        mask += [0] * pad
        types += [0] * pad
        return ids, mask, types

    def encode_batch(
        self, texts: Sequence[str], pairs: Optional[Sequence[str]] = None,
        max_len: int = 128,
    ):
        """Vectorized batch encode -> dict of (n, max_len) int32 arrays."""
        ids, masks, types = [], [], []
        for i, t in enumerate(texts):
            p = pairs[i] if pairs is not None else None
            a, m, ty = self.encode(str(t), p if p is None else str(p), max_len)
            ids.append(a)
            masks.append(m)
            types.append(ty)
        return {
            "input_ids": np.asarray(ids, np.int32),
            "attention_mask": np.asarray(masks, np.int32),
            "token_type_ids": np.asarray(types, np.int32),
        }

    # -- persistence -------------------------------------------------------
    def to_list(self) -> List[str]:
        return [self.inv[i] for i in range(len(self.inv))]

    @staticmethod
    def from_list(tokens: Sequence[str], do_lower_case: bool = True,
                  legacy: bool = False) -> "Tokenizer":
        return Tokenizer({t: i for i, t in enumerate(tokens)},
                         do_lower_case=do_lower_case, legacy=legacy)

    @property
    def vocab_size(self) -> int:
        return len(self.vocab)

"""Classical classification breadth: NaiveBayes, KNN, FM, MLP, OneVsRest.

Capability parity with the reference (reference:
core/src/main/java/com/alibaba/alink/operator/batch/classification/
NaiveBayesTrainBatchOp.java + operator/common/classification/NaiveBayesModelData.java,
KnnTrainBatchOp.java + operator/common/similarity/NearestNeighborsMapper,
FmClassifierTrainBatchOp.java + operator/common/optim/FmOptimizer.java:39,
MultilayerPerceptronTrainBatchOp.java +
operator/common/classification/ann/FeedForwardTopology.java / FeedForwardTrainer.java,
OneVsRestTrainBatchOp.java / OneVsRestModelMapper).

TPU-first re-design notes:
- NaiveBayes sufficient statistics are one-hot × feature matmuls on the MXU
  (the reference reduces per-row hash maps through AllReduce).
- KNN predict is a blocked dense distance matrix + ``lax.top_k`` on device —
  the per-row KD-tree/priority-queue of the reference collapses into one
  batched kernel.
- FM/MLP ride the shared distributed optimizer framework (`optim.optimize`)
  with flat-parameter objectives, exactly as the reference routes both through
  its Optimizer/FmOptimizer stack.
- OneVsRest packs the k sub-models into ONE model table with per-model key
  prefixes so the standard .ak / Pipeline persistence works unchanged.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ...common.exceptions import AkIllegalArgumentException, AkIllegalDataException
from ...common.linalg import pairwise_sq_dists
from ...common.model import model_to_table, table_to_model
from ...common.mtable import AlinkTypes, MTable
from ...common.params import InValidator, MinValidator, ParamInfo
from ...mapper import (
    HasFeatureCols,
    HasPredictionCol,
    HasPredictionDetailCol,
    HasReservedCols,
    HasVectorCol,
    RichModelMapper,
    get_feature_block,
    merge_feature_params,
    resolve_feature_cols,
    sigmoid_np,
    softmax_np,
)
from ...optim import fm_obj, fm_pairwise, mlp_forward, mlp_obj, optimize
from .base import BatchOperator
from .utils import ModelMapBatchOp, ModelTrainOpMixin


def _encode_labels(y_raw) -> tuple:
    labels = sorted(set(np.asarray(y_raw).tolist()), key=lambda v: str(v))
    lab_to_idx = {v: i for i, v in enumerate(labels)}
    idx = np.asarray([lab_to_idx[v] for v in np.asarray(y_raw).tolist()], np.int32)
    return labels, idx


def _train_features(op, t: MTable, label_col: str):
    vec_col = op.get(HasVectorCol.VECTOR_COL)
    if vec_col:
        feature_cols = None
        X = t.to_numeric_block([vec_col], dtype=np.float32)
    else:
        feature_cols = resolve_feature_cols(t, op, exclude=[label_col])
        X = t.to_numeric_block(feature_cols, dtype=np.float32)
    return X, feature_cols


# ---------------------------------------------------------------------------
# Naive Bayes
# ---------------------------------------------------------------------------

class NaiveBayesTrainBatchOp(ModelTrainOpMixin, BatchOperator, HasVectorCol,
                             HasFeatureCols):
    """(reference: operator/batch/classification/NaiveBayesTrainBatchOp.java —
    category/gaussian mixed features; here: modelType selects the likelihood)"""

    _min_inputs = 1
    _max_inputs = 1

    LABEL_COL = ParamInfo("labelCol", str, optional=False)
    MODEL_TYPE = ParamInfo(
        "modelType", str, default="GAUSSIAN",
        validator=InValidator("GAUSSIAN", "MULTINOMIAL", "BERNOULLI"),
    )
    SMOOTHING = ParamInfo("smoothing", float, default=1.0,
                          validator=MinValidator(0.0))

    def _static_meta_keys(self, in_schema):
        return {
            "modelName": "NaiveBayesModel",
            "labelType": in_schema.type_of(self.get(self.LABEL_COL)),
        }

    def _execute_impl(self, t: MTable) -> MTable:
        import jax
        import jax.numpy as jnp

        label_col = self.get(self.LABEL_COL)
        X, feature_cols = _train_features(self, t, label_col)
        labels, y = _encode_labels(t.col(label_col))
        k, d = len(labels), X.shape[1]
        alpha = self.get(self.SMOOTHING)
        mtype = self.get(self.MODEL_TYPE)

        @jax.jit
        def stats(X, y):
            onehot = jax.nn.one_hot(y, k, dtype=jnp.float32)  # (n, k)
            counts = onehot.sum(0)                             # per-class rows
            s1 = onehot.T @ X                                  # (k, d) sums
            s2 = onehot.T @ (X * X)                            # (k, d) sq sums
            sb = onehot.T @ (X > 0).astype(jnp.float32)        # (k, d) nnz
            return counts, s1, s2, sb

        counts, s1, s2, sb = map(np.asarray, jax.device_get(stats(X, y)))
        prior = np.log(counts / counts.sum())

        if mtype == "GAUSSIAN":
            mu = s1 / counts[:, None]
            var = s2 / counts[:, None] - mu * mu
            var = np.maximum(var, 1e-9) + alpha * 1e-9
            arrays = {"mu": mu.astype(np.float32), "var": var.astype(np.float32),
                      "prior": prior.astype(np.float32)}
        elif mtype == "MULTINOMIAL":
            theta = np.log((s1 + alpha) / (s1.sum(axis=1, keepdims=True) + alpha * d))
            arrays = {"theta": theta.astype(np.float32),
                      "prior": prior.astype(np.float32)}
        else:  # BERNOULLI
            p = (sb + alpha) / (counts[:, None] + 2.0 * alpha)
            arrays = {"logp": np.log(p).astype(np.float32),
                      "log1mp": np.log1p(-p).astype(np.float32),
                      "prior": prior.astype(np.float32)}

        meta = {
            "modelName": "NaiveBayesModel",
            "modelType": mtype,
            "vectorCol": self.get(HasVectorCol.VECTOR_COL),
            "featureCols": feature_cols,
            "labelCol": label_col,
            "labelType": t.schema.type_of(label_col),
            "labels": labels,
            "dim": int(d),
        }
        return model_to_table(meta, arrays)


def _build_nb_score(mtype: str):
    """Naive-Bayes scoring kernels with the model factors as ARGUMENTS —
    shared through the ProgramCache, one compile per (model type, shape
    bucket) across every model load (the three forms all reduce to matmuls
    against precomputed (a, b, c) factors)."""
    import jax
    import jax.numpy as jnp

    if mtype == "GAUSSIAN":
        def score(X, a, b, c):
            return -(X * X) @ a + X @ b + c
    elif mtype == "MULTINOMIAL":
        def score(X, a, b, c):
            return X @ a + c
    else:  # BERNOULLI
        def score(X, a, b, c):
            Xb = (X > 0).astype(jnp.float32)
            return Xb @ a + c

    return jax.jit(score)


class NaiveBayesModelMapper(RichModelMapper):
    """(reference: operator/common/classification/NaiveBayesModelMapper.java)"""

    def load_model(self, model: MTable):
        from ...common.jitcache import cached_jit

        self.meta, arrays = table_to_model(model)
        mtype = self.meta["modelType"]

        if mtype == "GAUSSIAN":
            mu, var, prior = arrays["mu"], arrays["var"], arrays["prior"]
            # log N(x|mu,var) summed over features, as three matmuls:
            # -0.5·x²·(1/var) + x·(mu/var) − 0.5·(mu²/var + log 2πvar)
            a = (1.0 / (2.0 * var)).T
            b = (mu / var).T
            c = (-0.5 * (mu * mu / var + np.log(2.0 * np.pi * var)).sum(1)
                 + prior)
        elif mtype == "MULTINOMIAL":
            theta, prior = arrays["theta"], arrays["prior"]
            a, b, c = theta.T, np.zeros((1, 1), np.float32), prior
        else:  # BERNOULLI
            logp, log1mp, prior = (arrays["logp"], arrays["log1mp"],
                                   arrays["prior"])
            a = (logp - log1mp).T
            b = np.zeros((1, 1), np.float32)
            c = log1mp.sum(1) + prior
        # staged to device ONCE — arguments to a shared program, without a
        # per-predict host→device re-transfer of the model factors
        from ...common import quant
        from ...common.jitcache import device_constants

        self._mtype = mtype
        self._policy = quant.policy_of(self.get_params())
        site = quant.site_of(self.get_params(), "naivebayes")
        self._site_x, self._site_xx = site + ".x", site + ".xx"
        a = np.asarray(a, np.float32)
        b = np.asarray(b, np.float32)
        c = np.asarray(c, np.float32)
        if self._policy == quant.BF16:
            a, b, c = (quant.bf16_round(v) for v in (a, b, c))
        self._score_factors = device_constants(a, b, c)
        self._score_jit = cached_jit("naivebayes.score", _build_nb_score,
                                     mtype)
        if self._policy == quant.INT8:
            aq, sa = quant.quantize_per_channel(a)
            bq, sb = quant.quantize_per_channel(b)
            self._q_factors = device_constants(
                aq, bq, c, np.asarray(sa, np.float32),
                np.asarray(sb, np.float32))
            self._score_q = quant.int8_nb_program(mtype)
        return self

    def _pred_type(self) -> str:
        return self.meta.get("labelType", AlinkTypes.STRING)

    def predict_proba_block(self, t: MTable):
        import jax

        from ...common.jitcache import call_row_bucketed

        from ...common import quant

        X = get_feature_block(
            t, merge_feature_params(self.get_params(), self.meta),
            vector_size=self.meta["dim"],
        ).astype(np.float32)
        if quant.capturing():
            quant.observe(self._site_x, X)
            if self._mtype == "GAUSSIAN":
                quant.observe(self._site_xx, X * X)
        if self._policy == quant.BF16:
            X = quant.bf16_round(X)
        if self._policy == quant.INT8:
            params = self.get_params()
            sx = np.float32(quant.calib_scale(params, self._site_x)
                            if self._mtype != "BERNOULLI" else 1.0)
            sxx = np.float32(quant.calib_scale(params, self._site_xx)
                             if self._mtype == "GAUSSIAN" else 1.0)
            s = np.asarray(jax.device_get(call_row_bucketed(
                self._score_q, (X,), self._q_factors + (sxx, sx))))
            return softmax_np(s)
        s = np.asarray(jax.device_get(call_row_bucketed(
            self._score_jit, (X,), self._score_factors)))
        return softmax_np(s)

    def predict_block(self, t: MTable):
        return self._classification_result(self.predict_proba_block(t))


class NaiveBayesPredictBatchOp(ModelMapBatchOp, HasPredictionCol,
                               HasPredictionDetailCol, HasReservedCols,
                               HasVectorCol, HasFeatureCols):
    mapper_cls = NaiveBayesModelMapper


# ---------------------------------------------------------------------------
# KNN
# ---------------------------------------------------------------------------

class KnnTrainBatchOp(ModelTrainOpMixin, BatchOperator, HasVectorCol,
                      HasFeatureCols):
    """Stores the training block — predict does the work (reference:
    operator/batch/classification/KnnTrainBatchOp.java builds the same
    "model = data" table via NearestNeighbor converters)."""

    _min_inputs = 1
    _max_inputs = 1

    LABEL_COL = ParamInfo("labelCol", str, optional=False)
    DISTANCE_TYPE = ParamInfo(
        "distanceType", str, default="EUCLIDEAN",
        validator=InValidator("EUCLIDEAN", "COSINE"),
    )

    def _static_meta_keys(self, in_schema):
        return {
            "modelName": "KnnModel",
            "labelType": in_schema.type_of(self.get(self.LABEL_COL)),
        }

    def _execute_impl(self, t: MTable) -> MTable:
        label_col = self.get(self.LABEL_COL)
        X, feature_cols = _train_features(self, t, label_col)
        labels, y = _encode_labels(t.col(label_col))
        meta = {
            "modelName": "KnnModel",
            "distanceType": self.get(self.DISTANCE_TYPE),
            "vectorCol": self.get(HasVectorCol.VECTOR_COL),
            "featureCols": feature_cols,
            "labelCol": label_col,
            "labelType": t.schema.type_of(label_col),
            "labels": labels,
            "dim": int(X.shape[1]),
        }
        return model_to_table(meta, {"X": X.astype(np.float32),
                                     "y": y.astype(np.int32)})


def _build_knn_classify(k_neighbors: int, num_labels: int, cosine: bool):
    """Top-k vote kernel with the training block as an ARGUMENT, shared
    through the ProgramCache across model loads with the same (k, labels,
    metric) config."""
    import jax
    import jax.numpy as jnp

    def knn(Q, X, y):
        if cosine:
            Qn = Q / jnp.maximum(jnp.linalg.norm(Q, axis=1, keepdims=True),
                                 1e-12)
            Xn = X / jnp.maximum(jnp.linalg.norm(X, axis=1, keepdims=True),
                                 1e-12)
            d = 1.0 - Qn @ Xn.T
        else:
            d = pairwise_sq_dists(Q, X)
        neg_d, idx = jax.lax.top_k(-d, k_neighbors)
        votes = jax.nn.one_hot(y[idx], num_labels).sum(axis=1)
        return votes, -neg_d

    return jax.jit(knn)


class KnnModelMapper(RichModelMapper):
    """Blocked brute-force top-k on device (reference:
    operator/common/classification/KnnMapper.java — per-row priority queue)."""

    K = ParamInfo("k", int, default=10, validator=MinValidator(1))

    def load_model(self, model: MTable):
        from ...common.jitcache import cached_jit

        self.meta, arrays = table_to_model(model)
        self.X_train = arrays["X"]
        self.y_train = arrays["y"]
        k_neighbors = min(self.get(self.K), self.X_train.shape[0])
        num_labels = len(self.meta["labels"])
        cosine = self.meta.get("distanceType") == "COSINE"
        from ...common.jitcache import device_constants

        self._train_dev = device_constants(self.X_train, self.y_train)
        self._knn_jit = cached_jit("knn.classify", _build_knn_classify,
                                   int(k_neighbors), int(num_labels),
                                   bool(cosine))
        return self

    def _pred_type(self) -> str:
        return self.meta.get("labelType", AlinkTypes.STRING)

    def predict_proba_block(self, t: MTable):
        import jax

        from ...common.jitcache import call_row_bucketed

        Q = get_feature_block(
            t, merge_feature_params(self.get_params(), self.meta),
            vector_size=self.meta["dim"],
        ).astype(np.float32)
        # per-query top-k is row-wise over Q — bucketing is bit-parity safe
        votes, _ = jax.device_get(call_row_bucketed(
            self._knn_jit, (Q,), self._train_dev))
        votes = np.asarray(votes)
        return votes / votes.sum(axis=1, keepdims=True)

    def predict_block(self, t: MTable):
        return self._classification_result(self.predict_proba_block(t))


class KnnPredictBatchOp(ModelMapBatchOp, HasPredictionCol,
                        HasPredictionDetailCol, HasReservedCols,
                        HasVectorCol, HasFeatureCols):
    mapper_cls = KnnModelMapper
    K = KnnModelMapper.K


class KnnRegTrainBatchOp(ModelTrainOpMixin, BatchOperator, HasVectorCol,
                         HasFeatureCols):
    """KNN regression: the model is the training block with float targets
    (reference: operator/batch/regression/KnnRegTrainBatchOp.java)."""

    _min_inputs = 1
    _max_inputs = 1

    LABEL_COL = ParamInfo("labelCol", str, optional=False)
    DISTANCE_TYPE = KnnTrainBatchOp.DISTANCE_TYPE

    def _static_meta_keys(self, in_schema):
        return {"modelName": "KnnRegModel"}

    def _execute_impl(self, t: MTable) -> MTable:
        label_col = self.get(self.LABEL_COL)
        X, feature_cols = _train_features(self, t, label_col)
        y = np.asarray(t.col(label_col), np.float32)
        meta = {
            "modelName": "KnnRegModel",
            "distanceType": self.get(self.DISTANCE_TYPE),
            "vectorCol": self.get(HasVectorCol.VECTOR_COL),
            "featureCols": feature_cols,
            "labelCol": label_col,
            "dim": int(X.shape[1]),
        }
        return model_to_table(meta, {"X": X.astype(np.float32),
                                     "y": y})


def _build_knn_reg(k: int, cosine: bool):
    import jax
    import jax.numpy as jnp

    def knn(Q, X, y):
        if cosine:
            Qn = Q / jnp.maximum(jnp.linalg.norm(Q, axis=1, keepdims=True),
                                 1e-12)
            Xn = X / jnp.maximum(jnp.linalg.norm(X, axis=1, keepdims=True),
                                 1e-12)
            d = 1.0 - Qn @ Xn.T
        else:
            d = pairwise_sq_dists(Q, X)
        neg_d, idx = jax.lax.top_k(-d, k)
        w = 1.0 / (jnp.sqrt(jnp.maximum(-neg_d, 0.0)) + 1e-6)
        return (w * y[idx]).sum(1) / w.sum(1)

    return jax.jit(knn)


class KnnRegModelMapper(RichModelMapper):
    """Inverse-distance-weighted mean of the k nearest targets."""

    K = ParamInfo("k", int, default=10, validator=MinValidator(1))

    def load_model(self, model: MTable):
        from ...common.jitcache import cached_jit

        self.meta, arrays = table_to_model(model)
        self.X_train = arrays["X"]
        self.y_train = arrays["y"].astype(np.float32)
        k = min(self.get(self.K), self.X_train.shape[0])
        cosine = self.meta.get("distanceType") == "COSINE"
        from ...common.jitcache import device_constants

        self._train_dev = device_constants(self.X_train, self.y_train)
        self._knn_jit = cached_jit("knn.regress", _build_knn_reg,
                                   int(k), bool(cosine))
        return self

    def _pred_type(self) -> str:
        return AlinkTypes.DOUBLE

    def predict_block(self, t: MTable):
        import jax

        from ...common.jitcache import call_row_bucketed

        Q = get_feature_block(
            t, merge_feature_params(self.get_params(), self.meta),
            vector_size=self.meta["dim"],
        ).astype(np.float32)
        pred = np.asarray(jax.device_get(call_row_bucketed(
            self._knn_jit, (Q,), self._train_dev)))
        return pred.astype(np.float64), AlinkTypes.DOUBLE, None


class KnnRegPredictBatchOp(ModelMapBatchOp, HasPredictionCol,
                           HasReservedCols, HasVectorCol, HasFeatureCols):
    mapper_cls = KnnRegModelMapper
    K = KnnRegModelMapper.K


# ---------------------------------------------------------------------------
# Factorization machines
# ---------------------------------------------------------------------------

class BaseFmTrainBatchOp(ModelTrainOpMixin, BatchOperator, HasVectorCol,
                         HasFeatureCols):
    """(reference: operator/batch/classification/FmClassifierTrainBatchOp.java,
    regression/FmRegressorTrainBatchOp.java → common/fm/BaseFmTrainBatchOp.java
    with FmOptimizer.java:39,80-84 adaptive SGD)"""

    _min_inputs = 1
    _max_inputs = 1

    fm_task: str = None  # binary | regression

    LABEL_COL = ParamInfo("labelCol", str, optional=False)
    NUM_FACTOR = ParamInfo("numFactor", int, default=10,
                           validator=MinValidator(1))
    MAX_ITER = ParamInfo("maxIter", int, default=100, validator=MinValidator(1))
    EPSILON = ParamInfo("epsilon", float, default=1e-6)
    LAMBDA_0 = ParamInfo("lambda0", float, default=0.0)
    LAMBDA_1 = ParamInfo("lambda1", float, default=0.0)
    LAMBDA_2 = ParamInfo("lambda2", float, default=0.0)
    INIT_STDEV = ParamInfo("initStdev", float, default=0.05)
    RANDOM_SEED = ParamInfo("randomSeed", int, default=0, aliases=("seed",))
    LEARN_RATE = ParamInfo("learnRate", float, default=0.1)

    def _static_meta_keys(self, in_schema):
        return {
            "modelName": "FmModel",
            "fmTask": self.fm_task,
            "labelType": in_schema.type_of(self.get(self.LABEL_COL)),
        }

    def _execute_impl(self, t: MTable) -> MTable:
        label_col = self.get(self.LABEL_COL)
        X, feature_cols = _train_features(self, t, label_col)
        n, d = X.shape
        kf = self.get(self.NUM_FACTOR)
        labels: Optional[List] = None
        if self.fm_task == "binary":
            labels, idx = _encode_labels(t.col(label_col))
            if len(labels) != 2:
                raise AkIllegalDataException(
                    f"FM classifier needs exactly 2 label values, got {len(labels)}"
                )
            y = np.where(idx == 0, 1.0, -1.0).astype(np.float32)
        else:
            y = np.asarray(t.col(label_col), np.float32)

        obj = fm_obj(d, kf, self.fm_task)
        rng = np.random.default_rng(self.get(self.RANDOM_SEED))
        w0 = np.zeros(obj.num_params, np.float32)
        # V must start non-zero: the pairwise term's gradient vanishes at V=0
        w0[1 + d:] = rng.normal(0.0, self.get(self.INIT_STDEV), d * kf)
        # per-block L2 as in the reference FmOptimizer: lambda0 on the
        # intercept, lambda1 on the linear weights, lambda2 on the factors
        l2_vec = np.concatenate([
            [self.get(self.LAMBDA_0)],
            np.full(d, self.get(self.LAMBDA_1)),
            np.full(d * kf, self.get(self.LAMBDA_2)),
        ]).astype(np.float32)
        res = optimize(
            obj, X, y, w0=w0,
            mesh=self.env.mesh,
            method="lbfgs",
            max_iter=self.get(self.MAX_ITER),
            l2=l2_vec,
            tol=self.get(self.EPSILON),
            learning_rate=self.get(self.LEARN_RATE),
        )
        w = res.weights
        meta = {
            "modelName": "FmModel",
            "fmTask": self.fm_task,
            "numFactor": kf,
            "vectorCol": self.get(HasVectorCol.VECTOR_COL),
            "featureCols": feature_cols,
            "labelCol": label_col,
            "labelType": t.schema.type_of(label_col),
            "labels": labels,
            "dim": int(d),
            "loss": res.loss,
            "numIters": res.num_iters,
        }
        arrays = {
            "w0": np.asarray([w[0]], np.float32),
            "w": np.asarray(w[1:1 + d], np.float32),
            "V": np.asarray(w[1 + d:], np.float32).reshape(d, kf),
        }
        return model_to_table(meta, arrays)


class FmClassifierTrainBatchOp(BaseFmTrainBatchOp):
    fm_task = "binary"


class FmRegressorTrainBatchOp(BaseFmTrainBatchOp):
    fm_task = "regression"


def _build_fm_score():
    import jax

    return jax.jit(lambda X, w0, w, V: w0[0] + X @ w + fm_pairwise(X, V))


class FmModelMapper(RichModelMapper):
    """(reference: operator/common/fm/FmModelMapper.java)"""

    def load_model(self, model: MTable):
        from ...common.jitcache import cached_jit

        from ...common.jitcache import device_constants

        from ...common import quant

        self.meta, arrays = table_to_model(model)
        self._policy = quant.policy_of(self.get_params())
        self._site = quant.site_of(self.get_params(), "fm") + ".x"
        w0 = arrays["w0"].astype(np.float32)
        w = arrays["w"].astype(np.float32)
        V = arrays["V"].astype(np.float32)
        if self._policy == quant.BF16:
            w0, w, V = (quant.bf16_round(v) for v in (w0, w, V))
        self._fm_params = device_constants(w0, w, V)
        # one process-wide FM scoring program (parameters as arguments):
        # every FM model load — batch predict or stream hot-swap — shares it
        self._score_jit = cached_jit("fm.score", _build_fm_score)
        if self._policy == quant.INT8:
            wq, sw = quant.quantize_per_channel(w)
            Vq, sv = quant.quantize_per_channel(V)
            self._fm_q = device_constants(
                w0, wq, Vq, np.asarray(sw, np.float32),
                np.asarray(sv, np.float32))
            self._score_q = quant.int8_fm_program()
        return self

    def _pred_type(self) -> str:
        if self.meta["fmTask"] == "regression":
            return AlinkTypes.DOUBLE
        return self.meta.get("labelType", AlinkTypes.STRING)

    def _scores(self, t: MTable) -> np.ndarray:
        import jax

        from ...common.jitcache import call_row_bucketed

        from ...common import quant

        X = get_feature_block(
            t, merge_feature_params(self.get_params(), self.meta),
            vector_size=self.meta["dim"],
        ).astype(np.float32)
        if quant.capturing():
            quant.observe(self._site, X)
        if self._policy == quant.BF16:
            X = quant.bf16_round(X)
        if self._policy == quant.INT8:
            sx = np.float32(quant.calib_scale(self.get_params(),
                                              self._site))
            return np.asarray(jax.device_get(call_row_bucketed(
                self._score_q, (X,), self._fm_q + (sx,))))
        return np.asarray(jax.device_get(call_row_bucketed(
            self._score_jit, (X,), self._fm_params)))

    def predict_proba_block(self, t: MTable):
        if self.meta["fmTask"] == "regression":
            return None
        prob_pos = sigmoid_np(self._scores(t))
        return np.stack([prob_pos, 1 - prob_pos], 1)

    def predict_block(self, t: MTable):
        if self.meta["fmTask"] == "regression":
            return self._scores(t).astype(np.float64), AlinkTypes.DOUBLE, None
        return self._classification_result(self.predict_proba_block(t))


class FmPredictBatchOp(ModelMapBatchOp, HasPredictionCol,
                       HasPredictionDetailCol, HasReservedCols,
                       HasVectorCol, HasFeatureCols):
    mapper_cls = FmModelMapper


class FmClassifierPredictBatchOp(FmPredictBatchOp):
    pass


class FmRegressorPredictBatchOp(FmPredictBatchOp):
    pass


# ---------------------------------------------------------------------------
# Multilayer perceptron
# ---------------------------------------------------------------------------

class MultilayerPerceptronTrainBatchOp(ModelTrainOpMixin, BatchOperator,
                                       HasVectorCol, HasFeatureCols):
    """(reference: operator/batch/classification/
    MultilayerPerceptronTrainBatchOp.java → FeedForwardTrainer over the
    distributed optimizer framework)"""

    _min_inputs = 1
    _max_inputs = 1

    LABEL_COL = ParamInfo("labelCol", str, optional=False)
    LAYERS = ParamInfo("layers", list, desc="hidden layer sizes", default=[16])
    MAX_ITER = ParamInfo("maxIter", int, default=100, validator=MinValidator(1))
    EPSILON = ParamInfo("epsilon", float, default=1e-6)
    L_2 = ParamInfo("l2", float, default=0.0, validator=MinValidator(0.0))
    RANDOM_SEED = ParamInfo("randomSeed", int, default=0, aliases=("seed",))

    def _static_meta_keys(self, in_schema):
        return {
            "modelName": "MlpModel",
            "labelType": in_schema.type_of(self.get(self.LABEL_COL)),
        }

    def _execute_impl(self, t: MTable) -> MTable:
        label_col = self.get(self.LABEL_COL)
        X, feature_cols = _train_features(self, t, label_col)
        labels, y = _encode_labels(t.col(label_col))
        d, k = X.shape[1], len(labels)
        sizes = [d] + [int(h) for h in self.get(self.LAYERS)] + [k]
        obj = mlp_obj(sizes)
        rng = np.random.default_rng(self.get(self.RANDOM_SEED))
        # Glorot-ish init per layer, biases zero
        w0 = np.zeros(obj.num_params, np.float32)
        off = 0
        for i in range(len(sizes) - 1):
            fan_in, fan_out = sizes[i], sizes[i + 1]
            w0[off:off + fan_in * fan_out] = rng.normal(
                0.0, np.sqrt(2.0 / (fan_in + fan_out)), fan_in * fan_out
            )
            off += fan_in * fan_out + fan_out
        res = optimize(
            obj, X, y.astype(np.float32), w0=w0,
            mesh=self.env.mesh, method="lbfgs",
            max_iter=self.get(self.MAX_ITER),
            l2=self.get(self.L_2), tol=self.get(self.EPSILON),
        )
        meta = {
            "modelName": "MlpModel",
            "layerSizes": sizes,
            "vectorCol": self.get(HasVectorCol.VECTOR_COL),
            "featureCols": feature_cols,
            "labelCol": label_col,
            "labelType": t.schema.type_of(label_col),
            "labels": labels,
            "dim": int(d),
            "loss": res.loss,
            "numIters": res.num_iters,
        }
        return model_to_table(meta, {"weights": res.weights.astype(np.float32)})


def _build_mlp_score(sizes: tuple):
    import jax

    return jax.jit(lambda X, w: mlp_forward(list(sizes), w, X))


class MlpModelMapper(RichModelMapper):
    """(reference: operator/common/classification/ann/MlpcModelMapper.java)"""

    def load_model(self, model: MTable):
        from ...common.jitcache import cached_jit

        from ...common.jitcache import device_constants

        from ...common import quant

        self.meta, arrays = table_to_model(model)
        self._policy = quant.policy_of(self.get_params())
        self._site = quant.site_of(self.get_params(), "mlp") + ".x"
        w = arrays["weights"].astype(np.float32)
        if self._policy == quant.BF16:
            w = quant.bf16_round(w)
        (self._mlp_w,) = device_constants(w)
        sizes = tuple(int(s) for s in self.meta["layerSizes"])
        self._score_jit = cached_jit("mlp.score", _build_mlp_score, sizes)
        if self._policy == quant.INT8:
            # unpack the flat LBFGS weight vector per mlp_forward's layout
            # ((fan_in, fan_out) matrix then (fan_out,) bias per layer) and
            # quantize each matrix per output channel
            packed = []
            off = 0
            for fi, fo in zip(sizes[:-1], sizes[1:]):
                W = w[off:off + fi * fo].reshape(fi, fo)
                off += fi * fo
                b = w[off:off + fo]
                off += fo
                Wq, s = quant.quantize_per_channel(W)
                packed += [Wq, np.asarray(s, np.float32), b]
            self._mlp_q = device_constants(*packed)
            self._score_q = quant.int8_mlp_program(sizes)
        return self

    def _pred_type(self) -> str:
        return self.meta.get("labelType", AlinkTypes.STRING)

    def predict_proba_block(self, t: MTable):
        import jax

        from ...common.jitcache import call_row_bucketed

        from ...common import quant

        X = get_feature_block(
            t, merge_feature_params(self.get_params(), self.meta),
            vector_size=self.meta["dim"],
        ).astype(np.float32)
        if quant.capturing():
            quant.observe(self._site, X)
        if self._policy == quant.BF16:
            X = quant.bf16_round(X)
        if self._policy == quant.INT8:
            logits = np.asarray(jax.device_get(call_row_bucketed(
                self._score_q, (X,), self._mlp_q)))
        else:
            logits = np.asarray(jax.device_get(call_row_bucketed(
                self._score_jit, (X,), (self._mlp_w,))))
        return softmax_np(logits)

    def predict_block(self, t: MTable):
        return self._classification_result(self.predict_proba_block(t))


class MultilayerPerceptronPredictBatchOp(ModelMapBatchOp, HasPredictionCol,
                                         HasPredictionDetailCol,
                                         HasReservedCols, HasVectorCol,
                                         HasFeatureCols):
    mapper_cls = MlpModelMapper


# ---------------------------------------------------------------------------
# One-vs-rest meta estimator
# ---------------------------------------------------------------------------

_OVR_POS, _OVR_NEG = "1", "2"  # "1" sorts first → positive class by convention


class OneVsRestTrainBatchOp(ModelTrainOpMixin, BatchOperator):
    """Trains one binary classifier per label value (reference:
    operator/batch/classification/OneVsRestTrainBatchOp.java).

    ``classifier`` is a prototype binary train op (e.g. a configured
    LogisticRegressionTrainBatchOp); it is cloned per class with the label
    column rewritten to a {pos, rest} indicator."""

    _min_inputs = 1
    _max_inputs = 1

    LABEL_COL = ParamInfo("labelCol", str)

    def __init__(self, classifier=None, params=None, **kwargs):
        super().__init__(params, **kwargs)
        if classifier is None:
            raise AkIllegalArgumentException(
                "OneVsRestTrainBatchOp needs a prototype binary classifier op"
            )
        self.classifier = classifier

    def _label_col(self):
        return self.get(self.LABEL_COL) or self.classifier.get_params().get("labelCol")

    def _static_meta_keys(self, in_schema):
        return {
            "modelName": "OneVsRestModel",
            "labelType": in_schema.type_of(self._label_col()),
        }

    def _execute_impl(self, t: MTable) -> MTable:
        from ..base import TableSourceOp
        from ...common.mtable import TableSchema

        label_col = self._label_col()
        labels, idx = _encode_labels(t.col(label_col))
        if len(labels) < 3:
            raise AkIllegalDataException(
                f"OneVsRest expects ≥3 label values, got {len(labels)}"
            )
        schema = TableSchema(
            list(t.schema.names),
            [AlinkTypes.STRING if n == label_col else t.schema.type_of(n)
             for n in t.schema.names],
        )
        sub_metas, all_arrays = [], {}
        for ci in range(len(labels)):
            relabel = np.where(idx == ci, _OVR_POS, _OVR_NEG).astype(object)
            cols = {n: t.col(n) for n in t.names}
            cols[label_col] = relabel
            sub_t = MTable(cols, schema)
            trainer = type(self.classifier)(self.classifier.get_params().clone())
            trainer.set("labelCol", label_col)
            model = trainer.link_from(TableSourceOp(sub_t))._evaluate()
            sub_meta, sub_arrays = table_to_model(model)
            sub_metas.append(sub_meta)
            for key, arr in sub_arrays.items():
                all_arrays[f"m{ci}:{key}"] = np.asarray(arr)
        meta = {
            "modelName": "OneVsRestModel",
            "labelCol": label_col,
            "labelType": t.schema.type_of(label_col),
            "labels": labels,
            "numClasses": len(labels),
            "subMetas": sub_metas,
            "mapperClass": getattr(
                type(self.classifier), "paired_mapper_cls_name", None
            ) or _fail_no_mapper(type(self.classifier).__name__),
        }
        return model_to_table(meta, all_arrays)


class OneVsRestModelMapper(RichModelMapper):
    """(reference: operator/common/classification/OneVsRestModelMapper.java —
    per-class probability from each sub-model's detail, argmax wins)"""

    def load_model(self, model: MTable):
        self.meta, arrays = table_to_model(model)
        mapper_cls = _resolve_mapper(self.meta["mapperClass"])
        self.sub_mappers = []
        for ci in range(self.meta["numClasses"]):
            prefix = f"m{ci}:"
            sub_arrays = {
                k[len(prefix):]: v for k, v in arrays.items()
                if k.startswith(prefix)
            }
            sub_model = model_to_table(self.meta["subMetas"][ci], sub_arrays)
            sub = mapper_cls(self.model_schema, self.data_schema,
                             self.get_params().clone())
            sub.load_model(sub_model)
            self.sub_mappers.append(sub)
        return self

    def _pred_type(self) -> str:
        return self.meta.get("labelType", AlinkTypes.STRING)

    def predict_proba_block(self, t: MTable):
        probs = []
        for sub in self.sub_mappers:
            sub_p = sub.predict_proba_block(t)
            pos = sub.meta["labels"].index(_OVR_POS)
            probs.append(np.asarray(sub_p[:, pos], np.float64))
        P = np.stack(probs, axis=1)  # (n, k) one-vs-rest positive probs
        return P / np.maximum(P.sum(axis=1, keepdims=True), 1e-12)

    def predict_block(self, t: MTable):
        return self._classification_result(self.predict_proba_block(t))


class OneVsRestPredictBatchOp(ModelMapBatchOp, HasPredictionCol,
                              HasPredictionDetailCol, HasReservedCols,
                              HasVectorCol, HasFeatureCols):
    mapper_cls = OneVsRestModelMapper


def _resolve_mapper(name: str):
    from .linear import LinearModelMapper

    base = {
        "LinearModelMapper": LinearModelMapper,
        "NaiveBayesModelMapper": NaiveBayesModelMapper,
        "FmModelMapper": FmModelMapper,
        "MlpModelMapper": MlpModelMapper,
        "KnnModelMapper": KnnModelMapper,
    }
    if name not in base:
        raise AkIllegalArgumentException(f"unknown OneVsRest base mapper {name}")
    return base[name]


def _fail_no_mapper(name: str):
    raise AkIllegalArgumentException(
        f"{name} declares no paired_mapper_cls_name; OneVsRest cannot serve it"
    )


NaiveBayesTrainBatchOp.paired_mapper_cls_name = "NaiveBayesModelMapper"
KnnTrainBatchOp.paired_mapper_cls_name = "KnnModelMapper"
BaseFmTrainBatchOp.paired_mapper_cls_name = "FmModelMapper"
MultilayerPerceptronTrainBatchOp.paired_mapper_cls_name = "MlpModelMapper"

from .mesh import (
    AXIS_DATA,
    AXIS_EXPERT,
    AXIS_MODEL,
    AXIS_SEQ,
    data_sharding,
    default_mesh,
    make_mesh,
    num_devices,
    pad_to_multiple,
    replicated_sharding,
)

"""Online serving tier: concurrent router, dynamic micro-batching, admission
control, breaker degradation, deadlines, HTTP surface, and the LocalPredictor
cached-plan parity contract.

The load-bearing guarantees pinned here:

- batched/concurrent results are BIT-IDENTICAL to serial LocalPredictor
  predicts (micro-batching only changes the leading kernel dimension, which
  the bucketing contract already pins as parity-safe);
- after load-time warmup, sustained mixed-batch-size load performs ZERO new
  traces (``jit.trace`` counter delta is 0 — the PR 4 contract carried to
  the serving tier);
- past-capacity load sheds gracefully: rejections are counted, accepted
  requests all complete (no deadlock), and their results stay bit-identical.

Pipelines here use StandardScaler + VectorAssembler + NaiveBayes — fit paths
that avoid the container's removed ``jax.shard_map`` (ROADMAP Open item 3);
the serving tier itself is model-agnostic.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from alink_tpu.common import MTable
from alink_tpu.common.metrics import metrics
from alink_tpu.common.exceptions import (
    AkCircuitOpenException,
    AkDeadlineExceededException,
    AkIllegalStateException,
    AkServingOverloadException,
)
from alink_tpu.pipeline import (
    LocalPredictor,
    NaiveBayes,
    Pipeline,
    StandardScaler,
    VectorAssembler,
)
from alink_tpu.serving import (
    ModelServer,
    ServingConfig,
    serving_bucket_ladder,
)
from alink_tpu.serving.router import _Request, PredictFuture

pytestmark = pytest.mark.serving

SCHEMA = "f0 double, f1 double, f2 double, f3 double"
FEATS = ["f0", "f1", "f2", "f3"]


def _make_data(n_per=60, seed=0):
    rng = np.random.default_rng(seed)
    X = np.concatenate([rng.normal(c, 0.4, size=(n_per, 4))
                        for c in [(0, 0, 0, 0), (2, 2, 2, 2)]])
    y = np.repeat(["neg", "pos"], n_per)
    t = MTable({f"f{i}": X[:, i] for i in range(4)}).with_column("label", y)
    return X, t


@pytest.fixture(scope="module")
def fitted():
    X, t = _make_data()
    model = Pipeline(
        StandardScaler(selectedCols=FEATS),
        VectorAssembler(selectedCols=FEATS, outputCol="vec"),
        NaiveBayes(vectorCol="vec", labelCol="label", predictionCol="pred"),
    ).fit(t)
    return X, t, model


@pytest.fixture(scope="module")
def serial_rows(fitted):
    """Ground truth: serial, uncached-plan, single-row predicts."""
    X, _, model = fitted
    lp = LocalPredictor(model, SCHEMA, cache_plan=False)
    return [lp.predict_row(tuple(r)) for r in X]


# ---------------------------------------------------------------------------
# LocalPredictor cached transform plan
# ---------------------------------------------------------------------------


def test_cached_plan_parity_with_uncached(fitted):
    """The construction-time transform plan returns bit-identical tables to
    rebuilding the DAG per call, across repeated mixed-size predicts."""
    X, t, model = fitted
    cached = LocalPredictor(model, SCHEMA)          # default: plan cached
    plain = LocalPredictor(model, SCHEMA, cache_plan=False)
    feat = t.select(FEATS)
    for n in (1, 3, 7, 20, 120, 5):                 # revisit sizes too
        assert cached.predict_table(feat.head(n)) == \
            plain.predict_table(feat.head(n))
    assert cached.predict_row(tuple(X[4])) == plain.predict_row(tuple(X[4]))
    assert cached.get_output_schema() == plain.get_output_schema()


def test_cached_plan_skips_replanning(fitted):
    """Repeated predicts reuse one plan: the op-node sub-DAG is built once
    (same object identity across calls)."""
    X, t, model = fitted
    cached = LocalPredictor(model, SCHEMA)
    cached.predict_table(t.select(FEATS).head(4))
    plan1 = cached._plan
    cached.predict_table(t.select(FEATS).head(9))
    assert cached._plan is plan1 and plan1 is not None


# ---------------------------------------------------------------------------
# Router: parity, batching, zero recompiles
# ---------------------------------------------------------------------------


def test_concurrent_results_bit_identical_to_serial(fitted, serial_rows):
    X, _, model = fitted
    srv = ModelServer(ServingConfig(max_batch_rows=16,
                                    flush_deadline_s=0.002))
    try:
        srv.load("parity", model, SCHEMA, warmup_rows=[tuple(X[0])])
        results = {}

        def client(cid):
            rows = [tuple(r) for r in X[cid::4]]
            results[cid] = srv.predict_many("parity", rows, timeout=60)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(4)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        for cid in range(4):
            assert results[cid] == serial_rows[cid::4], \
                f"client {cid} diverged from serial predicts"
        st = srv.stats()["models"][0]
        assert st["completed"] == len(X)
        # coalescing actually happened (fewer batches than requests)
        assert st["batches"] < st["completed"]
    finally:
        srv.close()


def test_zero_recompiles_under_sustained_mixed_load(fitted, serial_rows):
    """After load-time warmup of every ladder rung <= max_batch_rows,
    sustained concurrent mixed-batch-size load performs ZERO new traces."""
    X, _, model = fitted
    srv = ModelServer(ServingConfig(max_batch_rows=16,
                                    flush_deadline_s=0.001))
    try:
        srv.load("steady", model, SCHEMA, warmup_rows=[tuple(X[0])])
        traces0 = metrics.counter("jit.trace")
        compiles0 = metrics.counter("jit.compile")
        results = {}

        def client(cid):
            out = []
            for rep in range(3):  # several rounds => many distinct sizes
                rows = [tuple(r) for r in X[cid::5]]
                out.append(srv.predict_many("steady", rows, timeout=60))
            results[cid] = out

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(5)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert metrics.counter("jit.trace") == traces0
        assert metrics.counter("jit.compile") == compiles0
        for cid in range(5):
            for rep_out in results[cid]:
                assert rep_out == serial_rows[cid::5]
    finally:
        srv.close()


def test_default_warmup_synthesized_from_schema(fitted):
    """Omitting warmup_rows must not void the zero-traces contract: a zero
    sample row is synthesized from the (primitive-typed) input schema and
    every rung still warms at load."""
    X, _, model = fitted
    srv = ModelServer(ServingConfig(max_batch_rows=16,
                                    flush_deadline_s=0.001))
    try:
        info = srv.load("dwarm", model, SCHEMA)  # no warmup_rows
        assert info["warmup"]["rungs"] >= 2
        traces0 = metrics.counter("jit.trace")
        srv.predict_many("dwarm", [tuple(r) for r in X[:30]], timeout=60)
        assert metrics.counter("jit.trace") == traces0
    finally:
        srv.close()


def test_hot_swap_under_traffic_drops_nothing(fitted, serial_rows):
    """Requests racing a hot-swap re-route to the replacement entry instead
    of failing with 'model unloaded'."""
    X, _, model = fitted
    srv = ModelServer(ServingConfig(max_batch_rows=8,
                                    flush_deadline_s=0.001))
    try:
        srv.load("swaprace", model, SCHEMA, warmup_rows=[tuple(X[0])])
        stop = threading.Event()
        errors: list = []

        def hammer():
            i = 0
            while not stop.is_set():
                try:
                    got = srv.predict("swaprace", tuple(X[i % len(X)]),
                                      timeout=60)
                    assert got == serial_rows[i % len(X)]
                except Exception as e:  # noqa: BLE001 — collected for assert
                    errors.append(e)
                i += 1

        th = threading.Thread(target=hammer)
        th.start()
        for _ in range(5):
            srv.load("swaprace", model, SCHEMA, warmup_rows=[tuple(X[0])])
        stop.set()
        th.join(timeout=60)
        assert not errors, errors[:3]
    finally:
        srv.close()


def test_bucket_ladder_covers_every_batch_size():
    ladder = serving_bucket_ladder(64)
    from alink_tpu.common.jitcache import bucket_rows

    for n in range(1, 65):
        assert bucket_rows(n) in ladder


# ---------------------------------------------------------------------------
# Admission control: saturation, shedding, no deadlock
# ---------------------------------------------------------------------------


def test_saturation_sheds_gracefully(fitted, serial_rows):
    X, _, model = fitted
    srv = ModelServer(ServingConfig(queue_depth=8, max_batch_rows=8,
                                    flush_deadline_s=0.05))
    try:
        srv.load("sat", model, SCHEMA, warmup_rows=[tuple(X[0])])
        shed0 = metrics.counter("serving.shed")
        futs, shed = [], 0
        for i in range(300):
            try:
                futs.append((i % len(X),
                             srv.submit("sat", tuple(X[i % len(X)]))))
            except AkServingOverloadException:
                shed += 1
        assert shed > 0, "flood never hit the high-water mark"
        assert metrics.counter("serving.shed") >= shed0 + shed
        # no deadlock: every accepted request completes within the budget,
        # and bit-identical to the serial predicts
        for idx, fut in futs:
            assert fut.result(timeout=60) == serial_rows[idx]
        st = srv.stats()["models"][0]
        assert st["shed"] == shed
        assert st["completed"] == len(futs)
        assert st["queued"] == 0
    finally:
        srv.close()


def test_shed_policy_oldest_drops_queued_request(fitted):
    X, _, model = fitted
    # queue_depth < max_batch_rows and a long flush deadline: the batcher
    # waits for a fuller batch, so the queue stays full while we overflow it
    srv = ModelServer(ServingConfig(queue_depth=4, max_batch_rows=8,
                                    flush_deadline_s=10.0,
                                    shed_policy="oldest"))
    try:
        srv.load("oldest", model, SCHEMA)
        first = srv.submit("oldest", tuple(X[0]))
        rest = [srv.submit("oldest", tuple(X[i])) for i in range(1, 8)]
        # the overflow admissions dropped the oldest queued requests
        assert first.done()
        with pytest.raises(AkServingOverloadException):
            first.result(0)
        assert srv.stats()["models"][0]["shed"] > 0
        del rest
    finally:
        srv.close()


def test_deadline_expired_in_queue(fitted):
    X, _, model = fitted
    srv = ModelServer(ServingConfig(max_batch_rows=4,
                                    flush_deadline_s=0.2))
    try:
        srv.load("ddl", model, SCHEMA, warmup_rows=[tuple(X[0])])
        fut = srv.submit("ddl", tuple(X[0]), deadline_s=0.0)  # born expired
        with pytest.raises(AkDeadlineExceededException):
            fut.result(timeout=30)
        assert srv.stats()["models"][0]["deadline_expired"] == 1
    finally:
        srv.close()


def test_priority_lane_pops_first(fitted):
    """The batcher drains the priority lane before the normal lane."""
    X, _, model = fitted
    srv = ModelServer(ServingConfig(max_batch_rows=4,
                                    flush_deadline_s=10.0))
    try:
        srv.load("prio", model, SCHEMA)
        entry = srv._entry("prio")
        # inspect lane mechanics under the entry lock (the batcher cannot
        # pop while we hold it); lanes interleaved at submit time
        with entry._cond:
            reqs = [_Request(tuple(X[i]), PredictFuture(None, i % 2 == 0))
                    for i in range(6)]
            for r in reqs:
                (entry._high if r.future.priority else
                 entry._normal).append(r)
            batch = entry._pop_batch_locked()
            assert [r.future.priority for r in batch] == \
                [True] * 3 + [False] * 3
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# Breaker-gated degradation + lifecycle
# ---------------------------------------------------------------------------


class _BoomPredictor(LocalPredictor):
    """A predictor whose execution always fails — the unhealthy-model
    double for breaker tests."""

    def predict_table(self, t):
        raise RuntimeError("boom")


def test_breaker_degrades_failing_model_to_fast_rejects(fitted):
    X, _, model = fitted
    srv = ModelServer(ServingConfig(max_batch_rows=4, flush_deadline_s=0.001,
                                    breaker_threshold=2,
                                    breaker_reset_s=3600.0))
    try:
        srv.load("brk", _BoomPredictor(model, SCHEMA))
        # consecutive batch EXECUTION failures open the model's circuit
        for _ in range(2):
            with pytest.raises(RuntimeError):
                srv.predict("brk", tuple(X[0]), timeout=30)
        assert srv.stats()["models"][0]["breaker_open"]
        t0 = time.perf_counter()
        with pytest.raises(AkCircuitOpenException):
            srv.predict("brk", tuple(X[0]), timeout=30)
        assert time.perf_counter() - t0 < 5.0  # fast reject, not a hang
        assert srv.stats()["models"][0]["breaker_rejected"] >= 1
    finally:
        srv.close()


def test_bad_rows_rejected_per_request_without_tripping_breaker(fitted,
                                                                serial_rows):
    """Rows that cannot build against the input schema are CALLER errors:
    rejected individually, co-batched valid requests still answer, and the
    circuit never opens — one bad client cannot 503 a healthy model."""
    X, _, model = fitted
    srv = ModelServer(ServingConfig(max_batch_rows=8, flush_deadline_s=0.05,
                                    breaker_threshold=2,
                                    breaker_reset_s=3600.0))
    try:
        srv.load("badrows", model, SCHEMA, warmup_rows=[tuple(X[0])])
        for _ in range(3):  # well past the breaker threshold
            bad = srv.submit("badrows", ("boom", "x", "y", "z"))
            good = srv.submit("badrows", tuple(X[5]))
            with pytest.raises(Exception) as ei:
                bad.result(timeout=30)
            assert not isinstance(ei.value, AkCircuitOpenException)
            assert good.result(timeout=30) == serial_rows[5]
        st = srv.stats()["models"][0]
        assert not st["breaker_open"]
        assert st["bad_rows"] == 3
        assert st["completed"] >= 3
    finally:
        srv.close()


def test_hot_swap_gets_a_fresh_breaker(fitted, serial_rows):
    """A hot-swapped model must not inherit the retired entry's failure
    history: the new entry serves immediately even though the old one's
    circuit was open (and may keep failing while it drains)."""
    X, _, model = fitted
    srv = ModelServer(ServingConfig(max_batch_rows=4, flush_deadline_s=0.001,
                                    breaker_threshold=2,
                                    breaker_reset_s=3600.0))
    try:
        srv.load("swapbrk", _BoomPredictor(model, SCHEMA))
        for _ in range(2):
            with pytest.raises(RuntimeError):
                srv.predict("swapbrk", tuple(X[0]), timeout=30)
        assert srv.stats()["models"][0]["breaker_open"]
        srv.load("swapbrk", model, SCHEMA, warmup_rows=[tuple(X[0])])
        assert srv.predict("swapbrk", tuple(X[2]), timeout=30) == \
            serial_rows[2]
        assert not srv.stats()["models"][0]["breaker_open"]
    finally:
        srv.close()


def test_hot_swap_and_unload(fitted, serial_rows):
    X, t, model = fitted
    srv = ModelServer(ServingConfig(max_batch_rows=8,
                                    flush_deadline_s=0.002))
    try:
        srv.load("swap", model, SCHEMA, warmup_rows=[tuple(X[0])])
        assert srv.predict("swap", tuple(X[1]), timeout=30) == serial_rows[1]
        # hot-swap with a refit model: serving continues, new entry answers
        model2 = Pipeline(
            StandardScaler(selectedCols=FEATS),
            VectorAssembler(selectedCols=FEATS, outputCol="vec"),
            NaiveBayes(vectorCol="vec", labelCol="label",
                       predictionCol="pred"),
        ).fit(t)
        srv.load("swap", model2, SCHEMA, warmup_rows=[tuple(X[0])])
        assert srv.predict("swap", tuple(X[1]), timeout=30) == serial_rows[1]
        assert srv.unload("swap")
        assert not srv.unload("swap")
        with pytest.raises(Exception):
            srv.predict("swap", tuple(X[1]), timeout=5)
    finally:
        srv.close()


def test_unload_fails_fast_without_drain(fitted):
    X, _, model = fitted
    srv = ModelServer(ServingConfig(max_batch_rows=4,
                                    flush_deadline_s=10.0))
    try:
        srv.load("nodrain", model, SCHEMA)
        futs = [srv.submit("nodrain", tuple(X[i])) for i in range(3)]
        srv.unload("nodrain", drain=False)
        for f in futs:
            with pytest.raises(AkIllegalStateException):
                f.result(timeout=30)
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# Observability
# ---------------------------------------------------------------------------


def test_serving_spans_and_histograms(fitted):
    X, _, model = fitted
    from alink_tpu.common.tracing import tracer, tracing_enabled

    srv = ModelServer(ServingConfig(max_batch_rows=8,
                                    flush_deadline_s=0.002))
    try:
        srv.load("obs", model, SCHEMA, warmup_rows=[tuple(X[0])])
        srv.predict_many("obs", [tuple(r) for r in X[:10]], timeout=60)
        st = srv.stats()
        for h in ("serving.request_s", "serving.queue_s",
                  "serving.batch_rows"):
            assert st["histograms"][h]["count"] >= 10 or h == "serving.batch_rows"
            assert st["histograms"][h]["p99"] is not None
        if tracing_enabled():
            names = {s["name"] for s in tracer.spans()}
            assert "serving.batch" in names
            assert "serving.warmup" in names
        # Prometheus exposition carries the serving families
        from alink_tpu.common.metrics import export_prometheus

        text = export_prometheus()
        assert "alink_serving_request_seconds" in text
        assert "alink_serving_accepted_total" in text
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# HTTP surface
# ---------------------------------------------------------------------------


def _req(port, path, method="GET", body=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", method=method,
        data=None if body is None else json.dumps(body).encode())
    with urllib.request.urlopen(req, timeout=60) as r:
        return json.loads(r.read())


def test_http_serving_roundtrip(fitted, serial_rows, tmp_path):
    from alink_tpu.webui import ExperimentStore, WebUIServer

    X, _, model = fitted
    ak = str(tmp_path / "nb.ak")
    model.save(ak)
    srv = ModelServer(ServingConfig(max_batch_rows=8,
                                    flush_deadline_s=0.002))
    web = WebUIServer(port=0, store=ExperimentStore(
        str(tmp_path / "exp.json")), model_server=srv)
    web.start(background=True)
    try:
        out = _req(web.port, "/api/serving/models", "POST",
                   {"name": "nb", "path": ak, "inputSchema": SCHEMA,
                    "warmupRows": [list(map(float, X[0]))]})
        assert out["model"] == "nb" and out["warmup"]["rungs"] >= 1

        got = _req(web.port, "/api/serving/predict/nb", "POST",
                   {"row": list(map(float, X[3]))})
        exp = serial_rows[3]
        assert got["row"][:4] == pytest.approx([float(v) for v in exp[:4]])
        assert got["row"][-1] == exp[-1]

        many = _req(web.port, "/api/serving/predict/nb", "POST",
                    {"rows": [list(map(float, X[i])) for i in range(6)]})
        assert [r[-1] for r in many["rows"]] == \
            [serial_rows[i][-1] for i in range(6)]

        st = _req(web.port, "/api/serving")
        assert st["models"][0]["model"] == "nb"
        assert st["models"][0]["completed"] >= 7

        # unknown model → 400
        with pytest.raises(urllib.error.HTTPError) as ei:
            _req(web.port, "/api/serving/predict/ghost", "POST",
                 {"row": [1, 2, 3, 4]})
        assert ei.value.code == 400

        assert _req(web.port, "/api/serving/models/nb", "DELETE") == \
            {"unloaded": "nb"}
        with pytest.raises(urllib.error.HTTPError) as ei:
            _req(web.port, "/api/serving/models/nb", "DELETE")
        assert ei.value.code == 404
    finally:
        web.stop()
        srv.close()


def test_http_shed_maps_to_429(fitted, tmp_path):
    from alink_tpu.webui import ExperimentStore, WebUIServer

    X, _, model = fitted
    srv = ModelServer(ServingConfig(queue_depth=1, max_batch_rows=1,
                                    flush_deadline_s=5.0))
    srv.load("tiny", model, SCHEMA)
    # fill the queue out-of-band so the HTTP submit sheds
    srv.submit("tiny", tuple(X[0]))
    web = WebUIServer(port=0, store=ExperimentStore(
        str(tmp_path / "exp.json")), model_server=srv)
    web.start(background=True)
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _req(web.port, "/api/serving/predict/tiny", "POST",
                 {"row": list(map(float, X[1]))})
        assert ei.value.code == 429
    finally:
        web.stop()
        srv.close()


# ---------------------------------------------------------------------------
# persisted warmup specs (zero cold start: PR 11)
# ---------------------------------------------------------------------------


def test_warmup_sidecar_roundtrip_bit_identical(fitted, serial_rows,
                                                tmp_path):
    """The save side emits ``<model>.ak.warmup.json`` after a live warmup;
    a later load needs NOTHING but the path — schema and sample rows come
    from the sidecar — and serves bit-identical predictions with zero new
    traces under traffic (the replica-rollout contract)."""
    from alink_tpu.serving import load_warmup_spec, warmup_sidecar_path

    X, _, model = fitted
    ak = str(tmp_path / "m.ak")
    model.save(ak)
    srv = ModelServer(ServingConfig(max_batch_rows=16))
    try:
        info1 = srv.load("live", ak, SCHEMA, warmup_rows=[tuple(X[0])])
        assert info1["warmup_source"] == "caller"
        assert info1["warmup_sidecar"] == warmup_sidecar_path(ak)
        spec = load_warmup_spec(ak)
        assert spec["input_schema"].lower() == SCHEMA  # to_str upper-cases
        assert spec["warmup_rows"] == [tuple(map(float, X[0]))]
        assert spec["max_batch_rows"] == 16
        assert spec["ladder"] == serving_bucket_ladder(16)

        # the fresh-replica side: no schema, no rows — disk artifacts only
        info2 = srv.load("replica", ak)
        assert info2["warmup_source"] == "sidecar"
        # a sidecar-sourced load never rewrites the sidecar: replica loads
        # stay read-only against the model store
        assert info2["warmup_sidecar"] is None
        t0 = metrics.counter("jit.trace")
        got = [srv.predict("replica", tuple(r)) for r in X[:24]]
        assert metrics.counter("jit.trace") == t0, \
            "traffic after a sidecar-warmed load must not trace"
        assert got == serial_rows[:24]
    finally:
        srv.close()


def test_warmup_sidecar_corrupt_falls_back_to_live(fitted, serial_rows,
                                                   tmp_path):
    """A truncated sidecar must read as absent: the load falls back to the
    live (here: schema-synthesized) warmup path, counts the corruption, and
    still serves bit-identical results."""
    from alink_tpu.serving import warmup_sidecar_path

    X, _, model = fitted
    ak = str(tmp_path / "m.ak")
    model.save(ak)
    with open(warmup_sidecar_path(ak), "w") as f:
        f.write('{"version": 1, "warmup_rows": [[')   # truncated JSON
    e0 = metrics.counter("serving.warmup_spec_errors")
    srv = ModelServer(ServingConfig(max_batch_rows=16))
    try:
        info = srv.load("m", ak, SCHEMA)
        assert metrics.counter("serving.warmup_spec_errors") > e0
        assert info["warmup_source"] == "synthesized"
        got = [srv.predict("m", tuple(r)) for r in X[:8]]
        assert got == serial_rows[:8]
    finally:
        srv.close()


def test_warmup_sidecar_knob_off_writes_nothing(fitted, tmp_path,
                                                monkeypatch):
    import os

    from alink_tpu.serving import warmup_sidecar_path

    X, _, model = fitted
    ak = str(tmp_path / "m.ak")
    model.save(ak)
    monkeypatch.setenv("ALINK_SERVING_PERSIST_WARMUP", "0")
    srv = ModelServer(ServingConfig(max_batch_rows=16))
    try:
        info = srv.load("m", ak, SCHEMA, warmup_rows=[tuple(X[0])])
        assert info["warmup_sidecar"] is None
        assert not os.path.exists(warmup_sidecar_path(ak))
    finally:
        srv.close()


def test_load_path_needs_schema_or_sidecar(tmp_path, fitted):
    from alink_tpu.common.exceptions import AkIllegalArgumentException

    _, _, model = fitted
    ak = str(tmp_path / "m.ak")
    model.save(ak)
    srv = ModelServer()
    try:
        with pytest.raises(AkIllegalArgumentException):
            srv.load("m", ak)   # no schema anywhere
    finally:
        srv.close()


def test_warmup_sidecar_stale_after_model_retrain(fitted, tmp_path):
    """Retraining a model at the same path must invalidate the old sidecar
    (its schema/rows describe a DIFFERENT model): the load falls back to
    live warmup and counts the staleness — while a byte-preserving
    copy/re-save (the normal rollout) keeps the sidecar valid (the
    fingerprint is content, not mtime, so cp/gsutil-style distribution
    cannot void zero cold start)."""
    import os
    import shutil

    from alink_tpu.serving import load_warmup_spec, warmup_sidecar_path

    X, _, model = fitted
    ak = str(tmp_path / "m.ak")
    model.save(ak)
    srv = ModelServer(ServingConfig(max_batch_rows=16))
    try:
        srv.load("v1", ak, SCHEMA, warmup_rows=[tuple(X[0])])
        assert load_warmup_spec(ak) is not None
        # a copy with rewritten mtimes (every rollout tool) stays VALID
        ak2 = str(tmp_path / "copy.ak")
        shutil.copyfile(ak, ak2)
        shutil.copyfile(warmup_sidecar_path(ak), warmup_sidecar_path(ak2))
        st = os.stat(ak2)
        os.utime(ak2, (st.st_atime, st.st_mtime + 999))
        assert load_warmup_spec(ak2) is not None
        # "retrain": same path, different CONTENT
        _, t2 = _make_data(seed=9)
        Pipeline(
            StandardScaler(selectedCols=FEATS),
            VectorAssembler(selectedCols=FEATS, outputCol="vec"),
            NaiveBayes(vectorCol="vec", labelCol="label",
                       predictionCol="pred"),
        ).fit(t2).save(ak)
        s0 = metrics.counter("serving.warmup_spec_stale")
        assert load_warmup_spec(ak) is None
        assert metrics.counter("serving.warmup_spec_stale") > s0
        info = srv.load("v2", ak, SCHEMA)
        assert info["warmup_source"] == "synthesized"   # not the stale rows
    finally:
        srv.close()

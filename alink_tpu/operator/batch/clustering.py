"""Clustering operators — KMeans family.

Capability parity with the reference (reference:
core/src/main/java/com/alibaba/alink/operator/batch/clustering/
KMeansTrainBatchOp.java:59 — IterativeComQueue + AllReduce at :104-110;
KMeansPredictBatchOp + operator/common/clustering/kmeans/KMeansModelMapper.java;
KMeansModelInfoBatchOp).

TPU-first: Lloyd's iteration is ONE compiled XLA program — a ``lax.while_loop``
inside ``shard_map``; assignments are a (n_local, k) distance matrix and the
cluster sums are a single (k, n_local)×(n_local, d) matmul on the MXU, with one
``psum`` per iteration for (sums, counts). k-means++ seeding runs host-side on
a subsample (the reference's random-K init is also host-side).
"""

from __future__ import annotations

import json
from typing import Optional

import numpy as np

from ...common.exceptions import AkIllegalDataException
from ...parallel.shardmap import shard_map
from ...common.linalg import pairwise_sq_dists
from ...common.model import model_to_table, table_to_model
from ...common.mtable import AlinkTypes, MTable
from ...common.params import InValidator, MinValidator, ParamInfo
from ...mapper import (
    HasFeatureCols,
    HasPredictionCol,
    HasPredictionDetailCol,
    HasReservedCols,
    HasVectorCol,
    RichModelMapper,
    get_feature_block,
    resolve_feature_cols,
)
from ...parallel.comqueue import shard_rows
from ...parallel.mesh import AXIS_DATA, default_mesh
from .base import BatchOperator
from .utils import ModelMapBatchOp, ModelTrainOpMixin


class HasKMeansParams(HasVectorCol, HasFeatureCols):
    K = ParamInfo("k", int, default=2, validator=MinValidator(2))
    MAX_ITER = ParamInfo("maxIter", int, default=50, validator=MinValidator(1))
    EPSILON = ParamInfo("epsilon", float, default=1e-4)
    DISTANCE_TYPE = ParamInfo(
        "distanceType", str, default="EUCLIDEAN",
        validator=InValidator("EUCLIDEAN", "COSINE", "HAVERSINE"),
    )
    RANDOM_SEED = ParamInfo("randomSeed", int, default=0, aliases=("seed",))


def _kmeanspp_init(X: np.ndarray, k: int, seed: int) -> np.ndarray:
    """Greedy k-means++ seeding on (a subsample of) the data, host-side:
    each step draws 2+log2(k) candidates ∝ d² and keeps the one minimizing
    the resulting potential — robust to unlucky single draws."""
    rng = np.random.default_rng(seed)
    n = X.shape[0]
    if n > 10000:
        X = X[rng.choice(n, 10000, replace=False)]
        n = X.shape[0]
    n_cand = 2 + int(np.log2(max(k, 2)))
    centers = [X[rng.integers(n)]]
    d2 = ((X - centers[0]) ** 2).sum(axis=1)
    for _ in range(1, k):
        total = d2.sum()
        if total <= 0:
            centers.append(X[rng.integers(n)])
            continue
        cand_idx = np.searchsorted(
            np.cumsum(d2 / total), rng.random(n_cand)
        ).clip(0, n - 1)
        # candidate minimizing the new total potential wins
        cand_d2 = np.minimum(
            d2[None, :], ((X[None, :, :] - X[cand_idx, None, :]) ** 2).sum(axis=2)
        )
        best = int(np.argmin(cand_d2.sum(axis=1)))
        centers.append(X[cand_idx[best]])
        d2 = cand_d2[best]
    return np.stack(centers).astype(np.float32)


_EARTH_RADIUS_KM = 6371.0


def _haversine_dists(Xl, c):
    """(n, k) great-circle distances; rows are (lat, lon) in degrees
    (reference: common/distance/HaversineDistance.java)."""
    import jax.numpy as jnp

    a = jnp.deg2rad(Xl)[:, None, :]     # (n, 1, 2)
    b = jnp.deg2rad(c)[None, :, :]      # (1, k, 2)
    dlat = a[..., 0] - b[..., 0]
    dlon = a[..., 1] - b[..., 1]
    h = (jnp.sin(dlat / 2) ** 2
         + jnp.cos(a[..., 0]) * jnp.cos(b[..., 0]) * jnp.sin(dlon / 2) ** 2)
    return 2.0 * _EARTH_RADIUS_KM * jnp.arcsin(
        jnp.sqrt(jnp.clip(h, 0.0, 1.0)))


def _build_lloyd(mesh, k: int, max_iter: int, tol: float, metric: str):
    """Build the jitted Lloyd program for one (mesh, k, max_iter, tol,
    metric) config — registered once in the process-wide ProgramCache
    (common/jitcache.py) so repeated fits reuse one traced program instead
    of rebuilding the ``jax.jit(shard_map(...))`` closure per call."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    cosine = metric == "COSINE"
    axis = AXIS_DATA

    def body(Xl, maskl, c0):
        def assign(c, Xl):
            if cosine:
                cn = c / jnp.maximum(jnp.linalg.norm(c, axis=1, keepdims=True), 1e-12)
                d = 1.0 - Xl @ cn.T
            elif metric == "HAVERSINE":
                d = _haversine_dists(Xl, c)
            else:
                d = pairwise_sq_dists(Xl, c)
            return d

        def cond(carry):
            i, c, shift, _ = carry
            return jnp.logical_and(i < max_iter, shift > tol)

        def step(carry):
            i, c, _, _ = carry
            d = assign(c, Xl)
            a = jnp.argmin(d, axis=1)
            onehot = jax.nn.one_hot(a, k, dtype=Xl.dtype) * maskl[:, None]
            counts = jax.lax.psum(onehot.sum(0), axis)      # (k,)
            if metric == "HAVERSINE":
                # centroid = spherical mean (mean of unit 3-vectors): the
                # degree-mean breaks at the antimeridian
                lat = jnp.deg2rad(Xl[:, 0])
                lon = jnp.deg2rad(Xl[:, 1])
                xyz = jnp.stack([jnp.cos(lat) * jnp.cos(lon),
                                 jnp.cos(lat) * jnp.sin(lon),
                                 jnp.sin(lat)], axis=1)
                s = jax.lax.psum(onehot.T @ xyz, axis)       # (k, 3)
                m = s / jnp.maximum(
                    jnp.linalg.norm(s, axis=1, keepdims=True), 1e-12)
                lat_c = jnp.rad2deg(jnp.arcsin(jnp.clip(m[:, 2], -1.0, 1.0)))
                lon_c = jnp.rad2deg(jnp.arctan2(m[:, 1], m[:, 0]))
                c_new = jnp.where(counts[:, None] > 0,
                                  jnp.stack([lat_c, lon_c], axis=1), c)
            else:
                sums = jax.lax.psum(onehot.T @ Xl, axis)    # (k, d) MXU matmul
                c_new = jnp.where(counts[:, None] > 0,
                                  sums / counts[:, None], c)
                if cosine:
                    c_new = c_new / jnp.maximum(
                        jnp.linalg.norm(c_new, axis=1, keepdims=True), 1e-12
                    )
            shift = jnp.abs(c_new - c).max()
            return i + 1, c_new, shift, jnp.asarray(0.0)

        i, c, _, _ = jax.lax.while_loop(
            cond, step, (jnp.asarray(0), c0, jnp.asarray(jnp.inf), jnp.asarray(0.0))
        )
        # inertia against the FINAL centroids (the stored model), not the
        # pre-update centroids of the last step
        inertia = jax.lax.psum(
            (jnp.min(assign(c, Xl), axis=1) * maskl).sum(), axis
        )
        return c, i, inertia

    return jax.jit(
        shard_map(
            body, mesh=mesh, in_specs=(P(axis), P(axis), P()), out_specs=P(),
            check_vma=False,
        )
    )


def _lloyd(mesh, X: np.ndarray, k: int, max_iter: int, tol: float,
           metric, seed: int):
    """The compiled Lloyd loop. Returns (centroids, num_iters, inertia).
    ``metric``: "EUCLIDEAN" | "COSINE" | "HAVERSINE" (bool accepted for the
    legacy cosine flag)."""
    import jax
    import jax.numpy as jnp

    from ...common.jitcache import cached_jit

    if isinstance(metric, bool):
        metric = "COSINE" if metric else "EUCLIDEAN"
    if metric == "COSINE":
        X = X / np.maximum(np.linalg.norm(X, axis=1, keepdims=True), 1e-12)
    init = _kmeanspp_init(X, k, seed)
    Xs, mask = shard_rows(mesh, X, with_mask=True)
    f = cached_jit("kmeans.lloyd", _build_lloyd,
                   int(k), int(max_iter), float(tol), metric, mesh=mesh)
    c, iters, inertia = jax.device_get(f(Xs, mask, jnp.asarray(init)))
    return np.asarray(c), int(iters), float(inertia)


class KMeansTrainBatchOp(ModelTrainOpMixin, BatchOperator, HasKMeansParams):
    """(reference: operator/batch/clustering/KMeansTrainBatchOp.java)"""

    _min_inputs = 1
    _max_inputs = 1

    def _static_meta_keys(self, in_schema):
        return {"modelName": "KMeansModel"}

    def _execute_impl(self, t: MTable) -> MTable:
        k = self.get(self.K)
        feature_cols = (
            None
            if self.get(HasVectorCol.VECTOR_COL)
            else resolve_feature_cols(t, self)
        )
        X = get_feature_block(t, self).astype(np.float32)
        if X.shape[0] < k:
            raise AkIllegalDataException(
                f"k={k} but only {X.shape[0]} rows of data"
            )
        mesh = self.env.mesh
        c, iters, inertia = _lloyd(
            mesh, X, k, self.get(self.MAX_ITER), self.get(self.EPSILON),
            self.get(self.DISTANCE_TYPE), self.get(self.RANDOM_SEED),
        )
        meta = {
            "modelName": "KMeansModel",
            "k": k,
            "distanceType": self.get(self.DISTANCE_TYPE),
            "vectorCol": self.get(HasVectorCol.VECTOR_COL),
            "featureCols": feature_cols,
            "numIters": iters,
            "inertia": inertia,
            "dim": int(c.shape[1]),
        }
        return model_to_table(meta, {"centroids": c})


def _build_assign(metric: str):
    """Centroid-assignment kernel shared through the ProgramCache: centroids
    ride as an ARGUMENT (not a baked-in constant), so loading N copies of
    the same model — or N different models with the same metric — compiles
    once, not N times."""
    import jax
    import jax.numpy as jnp

    def assign(X, c):
        if metric == "COSINE":
            Xn = X / jnp.maximum(jnp.linalg.norm(X, axis=1, keepdims=True),
                                 1e-12)
            cn = c / jnp.maximum(jnp.linalg.norm(c, axis=1, keepdims=True),
                                 1e-12)
            d = 1.0 - Xn @ cn.T
        elif metric == "HAVERSINE":
            d = _haversine_dists(X, c)
        else:
            d = pairwise_sq_dists(X, c)
        return jnp.argmin(d, axis=1), d

    return jax.jit(assign)


class KMeansModelMapper(RichModelMapper):
    """(reference: operator/common/clustering/kmeans/KMeansModelMapper.java)"""

    def load_model(self, model: MTable):
        from ...common.jitcache import cached_jit, device_constants

        self.meta, arrays = table_to_model(model)
        self.centroids = arrays["centroids"].astype(np.float32)
        (self._centroids_dev,) = device_constants(self.centroids)
        metric = self.meta.get("distanceType", "EUCLIDEAN")
        # fetched from the process-wide ProgramCache: one compile per
        # (metric, shape bucket) across every model load in the process
        self._assign_jit = cached_jit("kmeans.assign", _build_assign, metric)
        return self

    def _pred_type(self) -> str:
        return AlinkTypes.LONG

    def predict_block(self, t: MTable):
        import jax

        from ...common.jitcache import call_row_bucketed
        from ...mapper import merge_feature_params

        X = get_feature_block(
            t, merge_feature_params(self.get_params(), self.meta),
            vector_size=self.meta["dim"],
        ).astype(np.float32)
        # row-bucketed: a batch-size sweep or ragged stream chunk reuses one
        # compiled program; argmin/distances are row-wise, so the padded run
        # is bit-identical to the unpadded one after the slice
        a, d = call_row_bucketed(self._assign_jit, (X,),
                                 (self._centroids_dev,))
        a, d = jax.device_get((a, d))
        detail = None
        if self.get(HasPredictionDetailCol.PREDICTION_DETAIL_COL):
            detail = np.asarray(
                [json.dumps({str(i): float(x) for i, x in enumerate(row)})
                 for row in d], dtype=object,
            )
        return a.astype(np.int64), AlinkTypes.LONG, detail


class KMeansPredictBatchOp(ModelMapBatchOp, HasPredictionCol,
                           HasPredictionDetailCol, HasReservedCols):
    """(reference: operator/batch/clustering/KMeansPredictBatchOp.java)"""

    mapper_cls = KMeansModelMapper


class KMeansModelInfoBatchOp(BatchOperator):
    """Cluster sizes/centroids view (reference: KMeansModelInfoBatchOp.java)."""

    _min_inputs = 1
    _max_inputs = 1

    def _execute_impl(self, model: MTable) -> MTable:
        meta, arrays = table_to_model(model)
        c = arrays["centroids"]
        return MTable(
            {
                "clusterId": np.arange(c.shape[0], dtype=np.int64),
                "center": [" ".join(format(v, "g") for v in row) for row in c],
            }
        )

    def _out_schema(self, in_schema):
        from ...common.mtable import TableSchema

        return TableSchema(["clusterId", "center"],
                           [AlinkTypes.LONG, AlinkTypes.STRING])


class GeoKMeansTrainBatchOp(KMeansTrainBatchOp):
    """KMeans over (lat, lon) degrees with great-circle distance
    (reference: operator/batch/clustering/GeoKMeansTrainBatchOp.java)."""

    LATITUDE_COL = ParamInfo("latitudeCol", str, optional=False)
    LONGITUDE_COL = ParamInfo("longitudeCol", str, optional=False)

    def _execute_impl(self, t: MTable) -> MTable:
        self.set(self.DISTANCE_TYPE, "HAVERSINE")
        self.set(HasFeatureCols.FEATURE_COLS,
                 [self.get(self.LATITUDE_COL), self.get(self.LONGITUDE_COL)])
        return super()._execute_impl(t)


class GeoKMeansPredictBatchOp(KMeansPredictBatchOp):
    pass

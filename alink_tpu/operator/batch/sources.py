"""Source/sink breadth: LibSvm, TFRecord, Parquet, Text, TSV.

Capability parity with the reference IO ops (reference:
core/src/main/java/com/alibaba/alink/operator/batch/source/
LibSvmSourceBatchOp.java (+ common/io/dummy LibSvm parsers),
TFRecordDatasetSourceBatchOp.java (+ common/dl/data/TFRecordReader.java),
ParquetSourceBatchOp.java (connectors/connector-parquet),
TextSourceBatchOp.java, TsvSourceBatchOp.java; sink counterparts under
operator/batch/sink/).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ...common.exceptions import AkIllegalArgumentException
from ...common.linalg import SparseVector, format_vector, parse_vector
from ...common.mtable import AlinkTypes, MTable, TableSchema
from ...common.params import ParamInfo
from ...io.filesystem import file_open
from .base import BatchOperator

_LIBSVM_SCHEMA = TableSchema(["label", "features"],
                             [AlinkTypes.DOUBLE, AlinkTypes.SPARSE_VECTOR])


class LibSvmSourceBatchOp(BatchOperator):
    """(label, sparse features) from LibSVM text (reference:
    LibSvmSourceBatchOp.java; startIndex handles 0/1-based feature ids).
    The sparse vectors stay sparse — they parse into SparseVector cells."""

    FILE_PATH = ParamInfo("filePath", str, optional=False)
    START_INDEX = ParamInfo("startIndex", int, default=1)

    _max_inputs = 0

    def _execute_impl(self) -> MTable:
        start = int(self.get(self.START_INDEX))
        labels: List[float] = []
        vecs: List[SparseVector] = []
        max_dim = 0
        parsed = []
        with file_open(self.get(self.FILE_PATH)) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                parts = line.split()
                labels.append(float(parts[0]))
                idx, vals = [], []
                for kv in parts[1:]:
                    k, v = kv.split(":")
                    idx.append(int(k) - start)
                    vals.append(float(v))
                parsed.append((idx, vals))
                if idx:
                    max_dim = max(max_dim, max(idx) + 1)
        for idx, vals in parsed:
            vecs.append(SparseVector(max_dim, idx, vals))
        return MTable(
            {"label": np.asarray(labels, np.float64),
             "features": np.asarray(vecs, object)}, _LIBSVM_SCHEMA)

    def _out_schema(self) -> TableSchema:
        return _LIBSVM_SCHEMA


class LibSvmSinkBatchOp(BatchOperator):
    """(reference: LibSvmSinkBatchOp.java)"""

    FILE_PATH = ParamInfo("filePath", str, optional=False)
    LABEL_COL = ParamInfo("labelCol", str, optional=False)
    VECTOR_COL = ParamInfo("vectorCol", str, optional=False)
    START_INDEX = ParamInfo("startIndex", int, default=1)

    _min_inputs = 1
    _max_inputs = 1

    def _execute_impl(self, t: MTable) -> MTable:
        start = int(self.get(self.START_INDEX))
        with file_open(self.get(self.FILE_PATH), "w") as f:
            for label, vec in zip(t.col(self.get(self.LABEL_COL)),
                                  t.col(self.get(self.VECTOR_COL))):
                v = parse_vector(vec)
                sv = v if isinstance(v, SparseVector) else None
                if sv is None:
                    dense = v.to_dense().data
                    items = [(i, x) for i, x in enumerate(dense) if x != 0]
                else:
                    items = list(zip(sv.indices.tolist(), sv.values.tolist()))
                body = " ".join(f"{int(i) + start}:{format(x, 'g')}"
                                for i, x in items)
                f.write(f"{format(float(label), 'g')} {body}\n")
        return t

    def _out_schema(self, in_schema):
        return in_schema


class TFRecordSourceBatchOp(BatchOperator):
    """tf.Example TFRecord file source (reference:
    TFRecordDatasetSourceBatchOp.java). schemaStr drives the per-column
    feature-kind mapping; vectors read from float lists."""

    FILE_PATH = ParamInfo("filePath", str, optional=False)
    SCHEMA_STR = ParamInfo("schemaStr", str, optional=False,
                           aliases=("schema",))

    _max_inputs = 0

    def _execute_impl(self) -> MTable:
        from ...common.linalg import DenseVector
        from ...io.tfrecord import decode_example, read_records

        schema = TableSchema.parse(self.get(self.SCHEMA_STR))
        rows = []
        for payload in read_records(self.get(self.FILE_PATH)):
            ex = decode_example(payload)
            row = []
            for n, tp in zip(schema.names, schema.types):
                kind, vals = ex.get(n, ("bytes", []))
                if AlinkTypes.is_vector(tp):
                    row.append(DenseVector(vals))
                elif tp == AlinkTypes.STRING:
                    row.append(vals[0].decode("utf-8") if vals else None)
                elif tp in (AlinkTypes.LONG, AlinkTypes.INT):
                    row.append(int(vals[0]) if vals else None)
                else:
                    row.append(float(vals[0]) if vals else None)
            rows.append(tuple(row))
        return MTable.from_rows(rows, schema)

    def _out_schema(self) -> TableSchema:
        return TableSchema.parse(self.get(self.SCHEMA_STR))


class TFRecordSinkBatchOp(BatchOperator):
    """(reference: TFRecordDatasetSinkBatchOp.java + ExampleCodingV2)"""

    FILE_PATH = ParamInfo("filePath", str, optional=False)

    _min_inputs = 1
    _max_inputs = 1

    def _execute_impl(self, t: MTable) -> MTable:
        from ...io.tfrecord import encode_example, write_records

        payloads = []
        for row in t.rows():
            features = {}
            for n, tp, v in zip(t.names, t.schema.types, row):
                if AlinkTypes.is_vector(tp):
                    features[n] = ("float", list(parse_vector(v).to_dense().data))
                elif tp == AlinkTypes.STRING:
                    features[n] = ("bytes", [] if v is None else [str(v)])
                elif tp in (AlinkTypes.LONG, AlinkTypes.INT,
                            AlinkTypes.BOOLEAN):
                    features[n] = ("int64", [] if v is None else [int(v)])
                else:
                    features[n] = ("float", [] if v is None else [float(v)])
            payloads.append(encode_example(features))
        write_records(self.get(self.FILE_PATH), payloads)
        return t

    def _out_schema(self, in_schema):
        return in_schema


class ParquetSourceBatchOp(BatchOperator):
    """(reference: ParquetSourceBatchOp.java via connector-parquet; here:
    pyarrow through pandas)"""

    FILE_PATH = ParamInfo("filePath", str, optional=False)

    _max_inputs = 0

    def _execute_impl(self) -> MTable:
        import pandas as pd

        with file_open(self.get(self.FILE_PATH), "rb") as f:
            df = pd.read_parquet(f)
        return MTable({c: df[c].to_numpy() for c in df.columns})

    def _out_schema(self) -> TableSchema:
        # parquet carries its own schema; a cheap metadata read avoids
        # loading the data (pyarrow reads the footer only)
        import pyarrow.parquet as pq

        with file_open(self.get(self.FILE_PATH), "rb") as f:
            pa_schema = pq.read_schema(f)
        names, types = [], []
        for field in pa_schema:
            names.append(field.name)
            s = str(field.type)
            if s.startswith("int"):
                types.append(AlinkTypes.LONG)
            elif s.startswith(("float", "double")):
                types.append(AlinkTypes.DOUBLE)
            elif s == "bool":
                types.append(AlinkTypes.BOOLEAN)
            else:
                types.append(AlinkTypes.STRING)
        return TableSchema(names, types)


class ParquetSinkBatchOp(BatchOperator):
    FILE_PATH = ParamInfo("filePath", str, optional=False)

    _min_inputs = 1
    _max_inputs = 1

    def _execute_impl(self, t: MTable) -> MTable:
        import pandas as pd

        data = {}
        for n, tp in zip(t.names, t.schema.types):
            col = t.col(n)
            if AlinkTypes.is_vector(tp):
                data[n] = [format_vector(parse_vector(v)) for v in col]
            else:
                data[n] = col
        with file_open(self.get(self.FILE_PATH), "wb") as f:
            pd.DataFrame(data).to_parquet(f, index=False)
        return t

    def _out_schema(self, in_schema):
        return in_schema


_TEXT_SCHEMA = TableSchema(["text"], [AlinkTypes.STRING])


class TextSourceBatchOp(BatchOperator):
    """One STRING column per line (reference: TextSourceBatchOp.java)."""

    FILE_PATH = ParamInfo("filePath", str, optional=False)
    TEXT_COL = ParamInfo("textCol", str, default="text")

    _max_inputs = 0

    def _execute_impl(self) -> MTable:
        with file_open(self.get(self.FILE_PATH)) as f:
            lines = [line.rstrip("\n") for line in f]
        col = self.get(self.TEXT_COL)
        return MTable({col: np.asarray(lines, object)},
                      TableSchema([col], [AlinkTypes.STRING]))

    def _out_schema(self) -> TableSchema:
        return TableSchema([self.get(self.TEXT_COL)], [AlinkTypes.STRING])


class TsvSourceBatchOp(BatchOperator):
    """Tab-separated, no quoting (reference: TsvSourceBatchOp.java)."""

    FILE_PATH = ParamInfo("filePath", str, optional=False)
    SCHEMA_STR = ParamInfo("schemaStr", str, optional=False,
                           aliases=("schema",))

    _max_inputs = 0

    def _execute_impl(self) -> MTable:
        schema = TableSchema.parse(self.get(self.SCHEMA_STR))
        rows = []
        with file_open(self.get(self.FILE_PATH)) as f:
            for line in f:
                line = line.rstrip("\n")
                if not line:
                    continue
                rows.append(tuple(line.split("\t")))
        return MTable.from_rows(rows, schema)

    def _out_schema(self) -> TableSchema:
        return TableSchema.parse(self.get(self.SCHEMA_STR))


class TsvSinkBatchOp(BatchOperator):
    FILE_PATH = ParamInfo("filePath", str, optional=False)

    _min_inputs = 1
    _max_inputs = 1

    def _execute_impl(self, t: MTable) -> MTable:
        with file_open(self.get(self.FILE_PATH), "w") as f:
            for row in t.rows():
                f.write("\t".join("" if v is None else str(v)
                                  for v in row) + "\n")
        return t

    def _out_schema(self, in_schema):
        return in_schema


class XlsSourceBatchOp(BatchOperator):
    """Excel sheet source, plugin-gated on openpyxl (reference:
    XlsSourceBatchOp.java via connectors/connector-xls)."""

    FILE_PATH = ParamInfo("filePath", str, optional=False)
    SCHEMA_STR = ParamInfo("schemaStr", str, optional=False,
                           aliases=("schema",))
    SHEET_NAME = ParamInfo("sheetName", str, default=None)
    IGNORE_FIRST_LINE = ParamInfo("ignoreFirstLine", bool, default=False)

    _max_inputs = 0

    def _execute_impl(self) -> MTable:
        import pandas as pd

        from ...common.exceptions import AkPluginNotExistException
        from ...common.mtable import TableSchema as _TS

        schema = _TS.parse(self.get(self.SCHEMA_STR))
        try:
            with file_open(self.get(self.FILE_PATH), "rb") as f:
                df = pd.read_excel(
                    f,
                    sheet_name=self.get(self.SHEET_NAME) or 0,
                    header=0 if self.get(self.IGNORE_FIRST_LINE) else None,
                    names=schema.names,
                )
        except ImportError as e:
            raise AkPluginNotExistException(
                "XlsSource needs the 'openpyxl' package (the connector-xls "
                "plugin analog): pip install openpyxl") from e
        return MTable({n: df[n].to_numpy() for n in schema.names}, schema)

    def _out_schema(self) -> TableSchema:
        return TableSchema.parse(self.get(self.SCHEMA_STR))

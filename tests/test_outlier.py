"""Outlier family tests (reference model: operator/batch/outlier tests,
e.g. BoxPlotOutlierBatchOpTest, IForestOutlierBatchOpTest)."""

import json

import numpy as np
import pytest

from alink_tpu.common.mtable import AlinkTypes, MTable
from alink_tpu.operator.batch import (
    MemSourceBatchOp,
    BoxPlotOutlierBatchOp,
    CopodOutlierBatchOp,
    EcodOutlierBatchOp,
    EsdOutlierBatchOp,
    EvalOutlierBatchOp,
    HbosOutlierBatchOp,
    IForestOutlierBatchOp,
    KdeOutlierBatchOp,
    KSigmaOutlier4GroupedDataBatchOp,
    KSigmaOutlierBatchOp,
    LofOutlierBatchOp,
    MadOutlierBatchOp,
    ShEsdOutlierBatchOp,
    TableSourceBatchOp,
)


def _series_with_spikes(n=200, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n)
    spike_idx = [20, 90, 150]
    x[spike_idx] = [12.0, -11.0, 14.0]
    return x, set(spike_idx)


def _blob_with_outliers(n=150, seed=1):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, 3)
    out_idx = [5, 60, 120]
    X[out_idx] = X[out_idx] + 10.0
    return X, set(out_idx)


@pytest.mark.parametrize("op_cls,kwargs", [
    (KSigmaOutlierBatchOp, {}),
    (BoxPlotOutlierBatchOp, {}),
    (MadOutlierBatchOp, {}),
    (EsdOutlierBatchOp, {}),
])
def test_univariate_detectors(op_cls, kwargs):
    x, spikes = _series_with_spikes()
    t = MTable({"v": x})
    out = op_cls(selectedCol="v", predictionCol="o",
                 predictionDetailCol="d", **kwargs).link_from(
        TableSourceBatchOp(t)
    ).collect()
    flags = np.asarray(out.col("o"))
    found = set(np.nonzero(flags)[0].tolist())
    assert spikes <= found, (spikes, found)
    assert len(found) <= 12  # no mass false positives
    s = json.loads(out.col("d")[20])["outlier_score"]
    assert s > json.loads(out.col("d")[0])["outlier_score"]


def test_shesd_seasonal():
    rng = np.random.RandomState(2)
    n, period = 240, 24
    seasonal = 5 * np.sin(2 * np.pi * np.arange(n) / period)
    x = seasonal + rng.randn(n) * 0.3
    x[100] += 4.0  # large vs the 0.3 residual noise, small vs the ±5 seasonal
    t = MTable({"v": x})
    out = ShEsdOutlierBatchOp(
        selectedCol="v", frequency=period, predictionCol="o"
    ).link_from(TableSourceBatchOp(t)).collect()
    flags = np.asarray(out.col("o"))
    assert flags[100]
    assert flags.sum() <= 8
    # plain ksigma on the raw series misses it (seasonal variance dominates)
    k_out = KSigmaOutlierBatchOp(selectedCol="v", predictionCol="o").link_from(
        TableSourceBatchOp(t)
    ).collect()
    assert not np.asarray(k_out.col("o"))[100]


@pytest.mark.parametrize("op_cls,kwargs", [
    (HbosOutlierBatchOp, {}),
    (KdeOutlierBatchOp, {}),
    (LofOutlierBatchOp, {"numNeighbors": 15}),
    (IForestOutlierBatchOp, {"numTrees": 50}),
    (EcodOutlierBatchOp, {}),
    (CopodOutlierBatchOp, {}),
])
def test_multivariate_detectors(op_cls, kwargs):
    X, outs = _blob_with_outliers()
    t = MTable({f"f{i}": X[:, i] for i in range(3)})
    op = op_cls(featureCols=[f"f{i}" for i in range(3)], predictionCol="o",
                predictionDetailCol="d", **kwargs).link_from(
        TableSourceBatchOp(t)
    )
    assert op.schema.type_of("o") == AlinkTypes.BOOLEAN  # static schema
    out = op.collect()
    scores = np.asarray(
        [json.loads(d)["outlier_score"] for d in out.col("d")]
    )
    # planted outliers are the top-scored rows
    top3 = set(np.argsort(-scores)[:3].tolist())
    assert top3 == outs, (op_cls.__name__, top3)


def test_grouped_ksigma():
    x1, s1 = _series_with_spikes(seed=3)
    x2 = np.random.RandomState(4).randn(200) * 100  # different scale group
    x2[7] = 5000.0
    t = MTable({
        "g": np.asarray(["a"] * 200 + ["b"] * 200, object),
        "v": np.concatenate([x1, x2]),
    })
    out = KSigmaOutlier4GroupedDataBatchOp(
        groupCols=["g"], selectedCol="v", predictionCol="o",
    ).link_from(TableSourceBatchOp(t)).collect()
    flags = np.asarray(out.col("o"))
    assert s1 <= set(np.nonzero(flags[:200])[0].tolist())
    assert flags[207]  # the group-b spike found at its own scale
    # group-a detection unaffected by group-b's 100x scale
    assert flags[:200].sum() <= 12


def test_eval_outlier():
    X, outs = _blob_with_outliers()
    y = np.zeros(len(X), np.int64)
    y[list(outs)] = 1
    t = MTable({**{f"f{i}": X[:, i] for i in range(3)}, "label": y})
    pred = IForestOutlierBatchOp(
        featureCols=[f"f{i}" for i in range(3)], predictionCol="o",
        predictionDetailCol="d", numTrees=50,
    ).link_from(TableSourceBatchOp(t))
    ev = EvalOutlierBatchOp(
        labelCol="label", predictionCol="o", predictionDetailCol="d",
    ).link_from(pred)
    m = ev.collect_metrics()
    assert m["Recall"] == 1.0
    assert m["AUC"] > 0.99
    assert m["Precision"] > 0.2


def test_eval_outlier_nan_prediction_not_outlier():
    """NaN predictions are missing, not outliers (ADVICE r4: bool(nan) is
    True, so a bare truth test counted every NaN as a detection)."""
    from alink_tpu.operator.batch import EvalOutlierBatchOp
    from alink_tpu.operator.batch.base import TableSourceBatchOp

    t = MTable({
        "label": np.array([1, 0, 0, 1], np.int64),
        "o": np.array([1.0, np.nan, 0.0, 1.0]),
    })
    m = EvalOutlierBatchOp(
        labelCol="label", predictionCol="o",
    ).link_from(TableSourceBatchOp(t)).collect_metrics()
    # row 1 (label 0, NaN pred) must count as a true negative: precision 1.0
    assert m["Precision"] == 1.0
    assert m["Recall"] == 1.0


def test_esd_nan_aware_and_ecod_left_tail():
    from alink_tpu.outlier import ecod, esd

    x, spikes = _series_with_spikes()
    x[10] = np.nan
    scores, flags = esd(x)
    assert spikes <= set(np.nonzero(flags)[0].tolist())
    assert not flags[10]

    # right-skewed column with a LOW outlier must still score highest
    rng = np.random.RandomState(5)
    col = np.exp(rng.randn(200))  # right-skewed
    col[17] = -50.0
    s, f = ecod(col[:, None])
    # the ECDF extremes tie (min's left tail == max's right tail), so the
    # planted low outlier is among the top-2 scores — before the fix its
    # score was ~0 (skew-selected right tail only)
    assert 17 in np.argsort(-s)[:2].tolist()
    assert f[17]


def test_lof_single_row():
    t = MTable({"a": np.asarray([1.0]), "b": np.asarray([2.0])})
    out = LofOutlierBatchOp(
        featureCols=["a", "b"], predictionCol="o"
    ).link_from(TableSourceBatchOp(t)).collect()
    assert not out.col("o")[0]


def test_sos_and_ocsvm_detectors():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(80, 2)).astype(np.float32)
    X[-1] = [8.0, 8.0]   # planted outlier
    from alink_tpu.outlier import ocsvm, sos

    s_scores, s_flags = sos(X)
    assert s_scores[-1] > np.median(s_scores[:-1])
    assert s_flags[-1]
    o_scores, o_flags = ocsvm(X, nu=0.05)
    assert o_scores[-1] > np.median(o_scores[:-1])
    assert o_flags[-1]


def test_sos_ocsvm_batch_ops():
    from alink_tpu.operator.batch import (OcsvmOutlierBatchOp,
                                          SosOutlierBatchOp)

    rng = np.random.default_rng(1)
    rows = [tuple(map(float, rng.normal(size=2))) for _ in range(60)]
    rows.append((9.0, 9.0))
    src = MemSourceBatchOp(rows, "x double, y double")
    for op_cls in (SosOutlierBatchOp, OcsvmOutlierBatchOp):
        out = op_cls(featureCols=["x", "y"]).link_from(src).collect()
        flags = np.asarray(out.col("pred"))
        assert flags[-1]


def test_outlier_stream_twins():
    from alink_tpu.operator.stream import TableSourceStreamOp
    from alink_tpu.operator.stream.outlier import (BoxPlotOutlierStreamOp,
                                                   KSigmaOutlierStreamOp)
    from alink_tpu.common.mtable import MTable

    rng = np.random.default_rng(2)
    vals = rng.normal(size=100)
    vals[10] = 40.0
    vals[60] = -35.0
    t = MTable({"v": vals})
    src = TableSourceStreamOp(t, chunkSize=50)
    out = KSigmaOutlierStreamOp(selectedCol="v", k=3.0).link_from(src) \
        .collect()
    flags = np.asarray(out.col("pred"))
    assert flags[10] and flags[60]
    assert flags.sum() <= 4
    out2 = BoxPlotOutlierStreamOp(selectedCol="v").link_from(src).collect()
    assert np.asarray(out2.col("pred"))[10]


def test_cooks_distance_outlier():
    from alink_tpu.operator.batch import CooksDistanceOutlierBatchOp

    rng = np.random.RandomState(0)
    X = rng.normal(size=(80, 2))
    y = X @ [1.0, 2.0] + rng.normal(0, 0.1, 80)
    X[0] = [6, 6]
    y[0] = -20  # high-leverage, high-residual point
    t = MTable({"a": X[:, 0], "b": X[:, 1], "y": y})
    out = CooksDistanceOutlierBatchOp(
        featureCols=["a", "b"], labelCol="y",
        predictionCol="o").link_from(TableSourceBatchOp(t)).collect()
    assert out.col("o")[0]
    assert out.col("o").sum() <= 5


def test_dbscan_outlier_and_grouped():
    from alink_tpu.operator.batch import (
        DbscanOutlier4GroupedDataBatchOp,
        DbscanOutlierBatchOp,
    )

    rng = np.random.RandomState(1)
    X = rng.normal(size=(100, 2))
    X[0] = [9, 9]
    t = MTable({"a": X[:, 0], "b": X[:, 1]})
    out = DbscanOutlierBatchOp(
        featureCols=["a", "b"],
        predictionCol="o").link_from(TableSourceBatchOp(t)).collect()
    assert out.col("o")[0] and out.col("o").sum() <= 5
    g = MTable({"g": np.repeat(["p", "q"], 50),
                "a": X[:, 0], "b": X[:, 1]})
    out = DbscanOutlier4GroupedDataBatchOp(
        groupCols=["g"], featureCols=["a", "b"],
        predictionCol="o").link_from(TableSourceBatchOp(g)).collect()
    assert out.col("o")[0]


def test_dtw_outlier():
    from alink_tpu.operator.batch import DynamicTimeWarpOutlierBatchOp

    x = np.sin(np.arange(200) * 0.3)
    x[100:110] += 4.0
    t = MTable({"v": x})
    out = DynamicTimeWarpOutlierBatchOp(
        selectedCol="v", seriesLength=10,
        predictionCol="o").link_from(TableSourceBatchOp(t)).collect()
    flagged = np.nonzero(out.col("o"))[0]
    assert len(flagged) > 0
    assert set(flagged).issubset(set(range(90, 130)))


def test_model_outlier_train_predict_roundtrip(tmp_path):
    """Train on clean data, flag a far point at serving time — the
    capability the transient detectors can't provide."""
    from alink_tpu.operator.batch import (
        IForestModelOutlierPredictBatchOp,
        IForestModelOutlierTrainBatchOp,
        OcsvmModelOutlierPredictBatchOp,
        OcsvmModelOutlierTrainBatchOp,
    )

    rng = np.random.RandomState(2)
    train = MTable({"a": rng.normal(size=200), "b": rng.normal(size=200)})
    test = MTable({"a": np.asarray([0.1, 12.0]),
                   "b": np.asarray([0.0, 12.0])})
    for train_op, pred_op in (
        (IForestModelOutlierTrainBatchOp(featureCols=["a", "b"],
                                         numTrees=50),
         IForestModelOutlierPredictBatchOp(predictionCol="o",
                                           predictionDetailCol="d")),
        (OcsvmModelOutlierTrainBatchOp(featureCols=["a", "b"], nu=0.05),
         OcsvmModelOutlierPredictBatchOp(predictionCol="o")),
    ):
        m = train_op.link_from(TableSourceBatchOp(train))
        out = pred_op.link_from(m, TableSourceBatchOp(test)).collect()
        assert not out.col("o")[0]  # inlier stays clean
        assert out.col("o")[1]      # far point flagged


def test_dbscan_model_family():
    from alink_tpu.operator.batch import (
        DbscanModelOutlierPredictBatchOp,
        DbscanPredictBatchOp,
        GroupDbscanModelBatchOp,
    )

    rng = np.random.RandomState(3)
    a = rng.normal(0, 0.2, size=(40, 2))
    b = rng.normal(5, 0.2, size=(40, 2))
    train = MTable({"x": np.r_[a[:, 0], b[:, 0]],
                    "y": np.r_[a[:, 1], b[:, 1]]})
    m = GroupDbscanModelBatchOp(featureCols=["x", "y"], epsilon=1.0,
                                minPoints=4).link_from(
        TableSourceBatchOp(train))
    test = MTable({"x": np.asarray([0.0, 5.0, 50.0]),
                   "y": np.asarray([0.0, 5.0, 50.0])})
    pred = DbscanPredictBatchOp(predictionCol="c").link_from(
        m, TableSourceBatchOp(test)).collect()
    c = pred.col("c")
    assert c[0] != c[1] and c[0] >= 0 and c[1] >= 0 and c[2] == -1
    out = DbscanModelOutlierPredictBatchOp(predictionCol="o").link_from(
        m, TableSourceBatchOp(test)).collect()
    assert out.col("o").tolist() == [False, False, True]


def test_grouped_stream_twins_generated():
    import alink_tpu.operator.stream as sm

    for n in ("KSigmaOutlier4GroupedDataStreamOp",
              "BoxPlotOutlier4GroupedDataStreamOp",
              "CopodOutlier4GroupedDataStreamOp",
              "EcodOutlier4GroupedDataStreamOp",
              "EsdOutlier4GroupedDataStreamOp",
              "HbosOutlier4GroupedDataStreamOp",
              "IForestOutlier4GroupedDataStreamOp",
              "OcsvmOutlier4GroupedDataStreamOp",
              "DbscanOutlier4GroupedDataStreamOp",
              "DynamicTimeWarpOutlierStreamOp",
              "SHEsdOutlierStreamOp"):
        assert hasattr(sm, n), n
    # a grouped twin actually runs per micro-batch
    from alink_tpu.operator.stream import TableSourceStreamOp

    rng = np.random.RandomState(4)
    t = MTable({"g": np.repeat(["p", "q"], 30),
                "v": np.r_[rng.normal(size=30), rng.normal(10, 1, 30)]})
    op = sm.KSigmaOutlier4GroupedDataStreamOp(
        groupCols=["g"], selectedCol="v", predictionCol="o").link_from(
        TableSourceStreamOp(t, numChunks=2))
    out = op.collect()
    assert out.num_rows == 60 and "o" in out.names

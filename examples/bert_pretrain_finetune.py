"""The real-text BERT story on the shipped corpora, end to end:

1. MLM-pretrain a tiny encoder on ``data/reviews_unlabeled.txt`` (async
   device-fed loop, per-epoch checkpoints with crash-resume);
2. export it as an HF-layout checkpoint dir (config.json +
   model.safetensors + vocab.txt);
3. fine-tune through ``BertTextClassifierTrainBatchOp`` with
   ``checkpointFilePath`` on the ``data/sst2_mini.csv`` train split;
4. report holdout accuracy on the held-out rows — the same split the
   BENCH ``bert_text_quality`` metric of record uses.

Runs in a few minutes on CPU. Scale ``--epochs``/``--finetune-epochs`` up
on an accelerator; ``bench.py`` runs the full-budget version.
"""

import argparse
import os
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--reviews", type=int, default=1500,
                    help="pretraining sentences (0 = full corpus)")
    ap.add_argument("--epochs", type=int, default=3, help="MLM epochs")
    ap.add_argument("--finetune-epochs", type=int, default=8)
    ap.add_argument("--checkpoint-dir", default=None,
                    help="pretrain checkpoint dir (enables crash-resume); "
                         "default: a temp dir")
    args = ap.parse_args()

    from alink_tpu.common.mtable import MTable
    from alink_tpu.dl.data import load_reviews, sst2_split
    from alink_tpu.dl.pretrain import pretrain_and_save
    from alink_tpu.operator.batch.base import TableSourceBatchOp
    from alink_tpu.operator.batch.dl import (
        BertTextClassifierPredictBatchOp, BertTextClassifierTrainBatchOp)

    stage = args.checkpoint_dir or tempfile.mkdtemp(prefix="alink_bert_pre_")

    # -- 1+2: pretrain on the unlabeled reviews, export HF layout ---------
    t0 = time.perf_counter()
    texts = load_reviews(limit=args.reviews or None)
    summary = pretrain_and_save(
        texts, stage, vocab_size=2000, hidden_size=96, num_layers=2,
        num_heads=4, intermediate_size=192, max_len=32, epochs=args.epochs,
        batch_size=64, learning_rate=3e-4, seed=0,
        # feed="async" is the default: masking + transfers run on the
        # transfer pool, double-buffered ahead of the jitted MLM step
        checkpoint_dir=os.path.join(stage, "_resume"))
    print(f"[1] pretrained on {len(texts)} sentences in "
          f"{time.perf_counter() - t0:.1f}s — MLM loss "
          f"{summary['initial_loss']} -> {summary['final_loss']}")
    print(f"[2] HF checkpoint at {stage}: "
          f"{sorted(f for f in os.listdir(stage) if not f.startswith('_'))}")

    # -- 3: fine-tune from the checkpoint on the sst2 train split ---------
    t1 = time.perf_counter()
    tr_t, tr_y, ho_t, ho_y = sst2_split(seed=0)
    model = BertTextClassifierTrainBatchOp(
        textCol="text", labelCol="label", checkpointFilePath=stage,
        maxSeqLength=32, numEpochs=args.finetune_epochs, batchSize=32,
        learningRate=5e-4, randomSeed=0,
        poolingStrategy="mean",  # NSP-less checkpoint: CLS slot untrained
    ).link_from(TableSourceBatchOp(MTable({"text": tr_t, "label": tr_y})))

    # -- 4: holdout accuracy on rows neither stage ever saw ---------------
    pred = BertTextClassifierPredictBatchOp(predictionCol="pred").link_from(
        model, TableSourceBatchOp(MTable({"text": ho_t, "label": ho_y}))
    ).collect()
    acc = float((np.asarray(pred.col("pred")) == ho_y).mean())
    print(f"[3] fine-tuned on {len(tr_t)} rows in "
          f"{time.perf_counter() - t1:.1f}s")
    print(f"[4] real-text holdout accuracy on {len(ho_t)} rows: {acc:.4f} "
          f"(coin flip = 0.50)")


if __name__ == "__main__":
    main()

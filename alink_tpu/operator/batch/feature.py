"""Feature engineering operators (first slice: assembler + scalers).

Capability parity with the reference (reference:
core/src/main/java/com/alibaba/alink/operator/batch/dataproc/vector/
VectorAssemblerBatchOp.java + common/dataproc/vector/VectorAssemblerMapper.java;
operator/batch/dataproc/StandardScalerTrainBatchOp.java + common/dataproc/
StandardScalerModelMapper.java; MinMaxScaler / MaxAbsScaler equivalents).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ...common.linalg import DenseVector
from ...common.model import model_to_table, table_to_model
from ...common.mtable import AlinkTypes, MTable
from ...common.params import ParamInfo
from ...mapper import (
    HasOutputCol,
    default_feature_cols,
    HasReservedCols,
    HasSelectedCols,
    Mapper,
    ModelMapper,
)
from .base import BatchOperator
from .utils import MapBatchOp, ModelMapBatchOp, ModelTrainOpMixin


class VectorAssemblerMapper(Mapper, HasSelectedCols, HasOutputCol, HasReservedCols):
    """Combine numeric/vector columns into one vector column."""

    def output_schema(self, input_schema):
        out = self.get(HasOutputCol.OUTPUT_COL) or "vec"
        return self._append_result_schema(input_schema, [out], [AlinkTypes.DENSE_VECTOR])

    def map_table(self, t: MTable) -> MTable:
        cols = self.get(HasSelectedCols.SELECTED_COLS) or default_feature_cols(
            t, include_vectors=True
        )
        out = self.get(HasOutputCol.OUTPUT_COL) or "vec"
        block = t.to_numeric_block(list(cols), dtype=np.float64)
        vecs = [DenseVector(row) for row in block]
        return self._append_result(
            t, {out: vecs}, {out: AlinkTypes.DENSE_VECTOR}
        )


class VectorAssemblerBatchOp(MapBatchOp, HasSelectedCols, HasOutputCol,
                             HasReservedCols):
    mapper_cls = VectorAssemblerMapper

    # plan validator (alink_tpu/analysis): assembled columns must be
    # numeric or vector — a STRING here fails inside to_numeric_block
    _plan_col_requirements = {"selectedCols": "numvec"}


class StandardScalerTrainBatchOp(ModelTrainOpMixin, BatchOperator, HasSelectedCols):
    """(reference: StandardScalerTrainBatchOp.java) — one distributed moment
    pass; model = (mean, std) per column."""

    WITH_MEAN = ParamInfo("withMean", bool, default=True)
    WITH_STD = ParamInfo("withStd", bool, default=True)

    _min_inputs = 1
    _max_inputs = 1

    # plan validator: selected columns feed the moment kernel — numeric only
    _plan_col_requirements = {"selectedCols": "numeric"}

    def _execute_impl(self, t: MTable) -> MTable:
        cols = list(self.get(HasSelectedCols.SELECTED_COLS) or
                    default_feature_cols(t))
        X = t.to_numeric_block(cols, dtype=np.float64)
        mean = X.mean(axis=0)
        # sample std (n-1), matching the reference's
        # TableSummary.standardDeviation (basicstatistic/TableSummary.java)
        std = X.std(axis=0, ddof=1) if X.shape[0] > 1 else np.ones(X.shape[1])
        meta = {
            "modelName": "StandardScalerModel",
            "selectedCols": cols,
            "withMean": self.get(self.WITH_MEAN),
            "withStd": self.get(self.WITH_STD),
        }
        return model_to_table(meta, {"mean": mean, "std": std})

    def _static_meta_keys(self, in_schema):
        cols = list(self.get(HasSelectedCols.SELECTED_COLS) or
                    default_feature_cols(in_schema))
        return {"modelName": "StandardScalerModel", "selectedCols": cols}


def _retype_double(schema, cols):
    from ...common.mtable import TableSchema

    types = [
        AlinkTypes.DOUBLE if n in cols else t
        for n, t in zip(schema.names, schema.types)
    ]
    return TableSchema(list(schema.names), types)


class StandardScalerModelMapper(ModelMapper, HasReservedCols):
    def load_model(self, model: MTable):
        self.meta, arrays = table_to_model(model)
        self.mean = arrays["mean"]
        self.std = np.where(arrays["std"] < 1e-12, 1.0, arrays["std"])
        return self

    def output_schema(self, input_schema):
        return _retype_double(input_schema, self.meta["selectedCols"])

    def map_table(self, t: MTable) -> MTable:
        cols = self.meta["selectedCols"]
        out = t
        for i, c in enumerate(cols):
            v = np.asarray(t.col(c), np.float64)
            if self.meta["withMean"]:
                v = v - self.mean[i]
            if self.meta["withStd"]:
                v = v / self.std[i]
            out = out.with_column(c, v, AlinkTypes.DOUBLE)
        return out


class StandardScalerPredictBatchOp(ModelMapBatchOp, HasReservedCols):
    mapper_cls = StandardScalerModelMapper


class MinMaxScalerTrainBatchOp(ModelTrainOpMixin, BatchOperator, HasSelectedCols):
    """(reference: MinMaxScalerTrainBatchOp.java)"""

    MIN = ParamInfo("min", float, default=0.0)
    MAX = ParamInfo("max", float, default=1.0)

    _min_inputs = 1
    _max_inputs = 1

    _plan_col_requirements = {"selectedCols": "numeric"}

    def _execute_impl(self, t: MTable) -> MTable:
        cols = list(self.get(HasSelectedCols.SELECTED_COLS) or
                    default_feature_cols(t))
        X = t.to_numeric_block(cols, dtype=np.float64)
        meta = {
            "modelName": "MinMaxScalerModel",
            "selectedCols": cols,
            "targetMin": self.get(self.MIN),
            "targetMax": self.get(self.MAX),
        }
        return model_to_table(
            meta, {"dataMin": X.min(axis=0), "dataMax": X.max(axis=0)}
        )

    def _static_meta_keys(self, in_schema):
        cols = list(self.get(HasSelectedCols.SELECTED_COLS) or
                    default_feature_cols(in_schema))
        return {"modelName": "MinMaxScalerModel", "selectedCols": cols}


class MinMaxScalerModelMapper(ModelMapper, HasReservedCols):
    def load_model(self, model: MTable):
        self.meta, arrays = table_to_model(model)
        self.dmin = arrays["dataMin"]
        rng = arrays["dataMax"] - arrays["dataMin"]
        self.range = np.where(rng < 1e-12, 1.0, rng)
        return self

    def output_schema(self, input_schema):
        return _retype_double(input_schema, self.meta["selectedCols"])

    def map_table(self, t: MTable) -> MTable:
        lo, hi = self.meta["targetMin"], self.meta["targetMax"]
        out = t
        for i, c in enumerate(self.meta["selectedCols"]):
            v = np.asarray(t.col(c), np.float64)
            v = (v - self.dmin[i]) / self.range[i] * (hi - lo) + lo
            out = out.with_column(c, v, AlinkTypes.DOUBLE)
        return out


class MinMaxScalerPredictBatchOp(ModelMapBatchOp, HasReservedCols):
    mapper_cls = MinMaxScalerModelMapper

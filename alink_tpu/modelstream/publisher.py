"""ModelStreamPublisher — the stream-train → serve loop.

At each epoch barrier of a recovering/elastic stream-training job (every
chain quiescent, operator state exactly the epoch snapshot's), the
publisher asks its bound train op for a servable model
(``op.servable_model()``), wraps it into a ``PipelineModel``, commits it
to a :class:`~alink_tpu.modelstream.store.ModelStreamStore` (blob →
warmup sidecar → manifest, the manifest rename being the atomic point),
and hot-swaps the committed version into a live :class:`ModelServer` —
or, when ``server`` is a :class:`~alink_tpu.serving.ServingFleet`,
broadcasts it into every replica (per-replica outcomes counted as
``modelstream.fleet_swap_ok``/``fleet_swap_missed``; a replica that
misses the swap re-syncs from ``store.latest()`` at health-recheck via
the bound model source) — continuously, under traffic, with bounded
staleness
(``ALINK_MODELSTREAM_MIN_EPOCH_S`` rate-limits publishes; ``0`` publishes
every epoch).

Crash-safety contract (drilled via the ``publish`` fault point's
``pre_blob``/``pre_sidecar``/``pre_manifest``/``pre_swap`` sites):

- the store publish runs BEFORE the training snapshot commits, so a crash
  anywhere in it rewinds to the previous epoch snapshot; deterministic
  retraining republishes the same epoch bit-identically over the debris;
- a crash after the manifest rename leaves the version fully durable —
  restart-resume (:meth:`resume`) swaps ``store.latest()`` into the
  server and republishing is idempotent by epoch;
- a consumer can never observe a torn model: the server only ever loads
  blobs whose manifest committed.

Swaps are zero-trace after the first load: model weights ride as
arguments through ``cached_jit``'s device_constants design, so each new
version reuses the compiled ladder programs (pinned by the
``modelstream.swap_trace_delta`` counter staying 0).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence

from ..common.env import env_float
from ..common.exceptions import AkIllegalArgumentException
from ..common.faults import maybe_fail
from ..common.metrics import metrics
from ..common.tracing import trace_span
from .store import ModelStreamStore

# event→servable staleness: sub-second epochs up to minutes-stale models
_LAG_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
                120.0, 300.0)


class ModelStreamPublisher:
    """Publish the model trained by ``chains[chain][ops][op_index]`` of a
    :class:`RecoverableStreamJob` / :class:`ElasticStreamJob` at every
    epoch barrier, and hot-swap it into ``server`` under ``name``.

    ``input_schema`` is the serving input schema (required when a server
    is attached — it rides the warmup sidecar so replicas warm from
    disk); ``warmup_rows`` optionally overrides the synthesized zero-row
    warmup sample; ``stage_params`` parameterizes the predict stage the
    model table is wrapped into (default ``predictionCol="pred"``).
    """

    def __init__(self, path: str, name: str, *,
                 server=None, chain: int = 0, op_index: int = 0,
                 input_schema=None,
                 warmup_rows: Optional[Sequence[Sequence]] = None,
                 stage_params: Optional[Dict[str, Any]] = None,
                 serving_config=None,
                 keep: Optional[int] = None,
                 min_epoch_s: Optional[float] = None):
        self.store = ModelStreamStore(path, keep=keep)
        self.name = name
        self.server = server
        self.chain = int(chain)
        self.op_index = int(op_index)
        if hasattr(input_schema, "to_str"):
            input_schema = input_schema.to_str()
        if server is not None and input_schema is None:
            raise AkIllegalArgumentException(
                "ModelStreamPublisher needs input_schema when a server is "
                "attached (it rides the warmup sidecar each swap consumes)")
        self.input_schema = input_schema
        self.warmup_rows = [tuple(r) for r in warmup_rows] \
            if warmup_rows else None
        if server is not None and hasattr(server, "bind_model_source"):
            # fleet target: a replica that missed a broadcast swap (or a
            # fresh respawn) re-syncs from the newest committed store
            # version at its next health-recheck
            server.bind_model_source(name, self._latest_blob)
        self.stage_params = dict(stage_params or {"predictionCol": "pred"})
        self.serving_config = serving_config
        self.min_epoch_s = float(min_epoch_s) if min_epoch_s is not None \
            else (env_float("ALINK_MODELSTREAM_MIN_EPOCH_S", 0.0) or 0.0)
        self._last_pub_t: Optional[float] = None
        self._swapped_epoch: Optional[int] = None
        self._first_swap_done = False
        self._publish_log: List[Dict[str, Any]] = []

    # -- job-build validation ------------------------------------------------
    def validate_target(self, op, *, keyed: bool = False) -> None:
        """Called by the job at build time with the op this publisher is
        bound to. Stamps the op for the ALK109 pre-flight rule and refuses
        shapes the barrier hook cannot serve."""
        if keyed:
            raise AkIllegalArgumentException(
                "ModelStreamPublisher requires a global (non-keyed) train "
                f"chain; chain {self.chain} is keyed — its model state is "
                "split across partitions at the barrier")
        if not hasattr(op, "servable_model"):
            raise AkIllegalArgumentException(
                f"{type(op).__name__} has no servable_model() — it cannot "
                "feed a ModelStreamPublisher")
        op._modelstream_bound = True

    # -- epoch-barrier protocol (driven by the coordinators) -----------------
    def publish_epoch(self, op, epoch: int, *, final: bool = False
                      ) -> Optional[str]:
        """Store-side publish for ``epoch`` — blob, sidecar, manifest, in
        that order, each behind its ``publish`` fault site. Runs BEFORE
        the epoch's training snapshot commits (chains parked), so any
        crash here rewinds training to the previous snapshot and the
        deterministic retrain republishes bit-identically. Returns the
        committed blob path, or None when skipped (throttled / model not
        ready yet)."""
        now = time.perf_counter()
        if self.min_epoch_s > 0 and not final \
                and self._last_pub_t is not None \
                and (now - self._last_pub_t) < self.min_epoch_s:
            metrics.incr("modelstream.throttled")
            return None
        model = op.servable_model()
        if model is None:
            metrics.incr("modelstream.unready")
            return None
        with trace_span("modelstream.publish", epoch=epoch,
                        model=self.name):
            fresh = not self.store.committed(epoch)
            pm = self._wrap(model)
            blob = self.store.publish(
                epoch, pm.save,
                write_sidecar=self._write_sidecar
                if self.input_schema is not None else None,
                meta={"model": self.name, "final": bool(final)})
        if fresh:
            metrics.incr("modelstream.publishes")
            self._publish_log.append({"epoch": int(epoch),
                                      "final": bool(final)})
        self._last_pub_t = time.perf_counter()
        return blob

    def swap_epoch(self, epoch: int, epoch_t0: Optional[float] = None
                   ) -> bool:
        """Serve-side swap, run AFTER the epoch's snapshot manifest
        committed. No-op when ``epoch`` was never committed to the store
        (throttled or unready at publish time)."""
        if not self.store.committed(epoch):
            return False
        maybe_fail("publish", label=f"epoch{epoch}.pre_swap")
        self._swap(epoch)
        if epoch_t0 is not None:
            metrics.observe("modelstream.lag_s",
                            time.perf_counter() - epoch_t0,
                            buckets=_LAG_BUCKETS)
        return True

    def resume(self) -> Optional[int]:
        """Heal after a restart: swap the newest committed version into
        the server (covers a crash at ``pre_swap`` — version durable, swap
        never ran — including on the job's final epoch). Idempotent."""
        latest = self.store.latest()
        if latest is None:
            return None
        epoch, _ = latest
        if self._swapped_epoch is None or self._swapped_epoch < epoch \
                or not self._server_has_model():
            self._swap(epoch)
            metrics.incr("modelstream.resumes")
        return epoch

    # -- internals -----------------------------------------------------------
    def _latest_blob(self) -> Optional[str]:
        latest = self.store.latest()
        return self.store.blob_path(latest[0]) if latest else None

    def _server_has_model(self) -> bool:
        if self.server is None:
            return True
        if hasattr(self.server, "has_model"):
            return bool(self.server.has_model(self.name))
        return self.name in getattr(self.server, "_entries", {})

    def _wrap(self, model_table):
        """Wrap a raw model table into the PipelineModel its ``modelName``
        names — the exact artifact ``PipelineModel.load``/``LocalPredictor``
        consume, so served-vs-local parity is definitional."""
        from ..common.model import table_to_model
        from ..pipeline.estimators import FmModel, LinearModel
        from ..pipeline.pipeline import PipelineModel

        meta, _ = table_to_model(model_table)
        model_name = meta.get("modelName")
        cls = {"LinearModel": LinearModel, "FmModel": FmModel}.get(
            str(model_name))
        if cls is None:
            raise AkIllegalArgumentException(
                f"no servable pipeline stage for modelName={model_name!r}")
        stage = cls(**self.stage_params)
        stage.set_model_data(model_table)
        return PipelineModel(stage)

    def _write_sidecar(self, blob_path: str, sidecar_path: str) -> None:
        from ..common.jitcache import bucket_rows
        from ..common.mtable import TableSchema
        from ..serving.router import (ServingConfig, _schema_zero_rows,
                                      serving_bucket_ladder)
        from ..serving.warmup_store import save_warmup_spec

        rows = self.warmup_rows
        if not rows:
            rows = _schema_zero_rows(
                TableSchema.parse(self.input_schema)) or []
        cfg = self.serving_config or \
            (self.server._config if self.server is not None
             else ServingConfig.default())
        mbr = bucket_rows(cfg.max_batch_rows)
        save_warmup_spec(blob_path,
                         input_schema=self.input_schema,
                         warmup_rows=rows,
                         max_batch_rows=mbr,
                         ladder=serving_bucket_ladder(mbr),
                         synthetic_rows=not bool(self.warmup_rows),
                         path=sidecar_path,
                         fsync=True)

    def _swap(self, epoch: int) -> None:
        self._swapped_epoch = int(epoch)
        if self.server is None:
            return
        blob = self.store.blob_path(epoch)
        before = metrics.counter("jit.trace")
        t0 = time.perf_counter()
        with trace_span("modelstream.swap", epoch=epoch, model=self.name):
            out = self.server.load(self.name, blob, self.input_schema,
                                   config=self.serving_config)
        metrics.add_time("modelstream.swap_s", time.perf_counter() - t0)
        if isinstance(out, dict) and "replicas" in out:
            # fleet-wide broadcast: per-replica outcome accounting; a
            # replica that missed it re-syncs from the bound store source
            for rep_out in out["replicas"].values():
                metrics.incr("modelstream.fleet_swap_ok"
                             if rep_out.get("ok")
                             else "modelstream.fleet_swap_missed")
        delta = metrics.counter("jit.trace") - before
        if self._first_swap_done and delta:
            # traces during a hot-swap mean the ladder keys were NOT
            # shared across versions — the zero-trace contract broke
            metrics.incr("modelstream.swap_trace_delta", delta)
        self._first_swap_done = True

    # -- readout -------------------------------------------------------------
    def summary(self) -> Dict[str, Any]:
        latest = self.store.latest()
        return {
            "model": self.name,
            "store": self.store.path,
            "versions": self.store.versions(),
            "latest_epoch": latest[0] if latest else None,
            "swapped_epoch": self._swapped_epoch,
            "published": list(self._publish_log),
        }


def modelstream_summary() -> Dict[str, Any]:
    """One-call readout of the publish loop's counters/latencies (the
    ``recovery_summary()``/``serving_summary()`` convention)."""
    out: Dict[str, Any] = {"counters": metrics.counters("modelstream.")}
    lag = metrics.histogram("modelstream.lag_s")
    if lag:
        out["lag_s"] = lag
    swap = metrics.timer_stats("modelstream.swap_s")
    if swap:
        out["swap_s"] = swap
    return out

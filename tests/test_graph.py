"""Graph algorithm tests.

Mirrors the reference tests (reference: core/src/test/java/com/alibaba/alink/
operator/batch/graph/PageRankBatchOpTest.java,
ConnectedComponentsBatchOpTest.java, KCoreBatchOpTest.java, ...)."""

import numpy as np
import pytest

from alink_tpu.common.mtable import MTable
from alink_tpu.operator.batch.base import TableSourceBatchOp

from alink_tpu.operator.batch import (
    CommonNeighborsBatchOp,
    CommunityDetectionClusterBatchOp,
    ConnectedComponentsBatchOp,
    EdgeClusterCoefficientBatchOp,
    KCoreBatchOp,
    LouvainBatchOp,
    MemSourceBatchOp,
    ModularityCalBatchOp,
    PageRankBatchOp,
    SingleSourceShortestPathBatchOp,
    TriangleListBatchOp,
    VertexClusterCoefficientBatchOp,
)


def _edges(pairs, weights=None):
    if weights is None:
        return MemSourceBatchOp([(a, b) for a, b in pairs],
                                "source string, target string")
    return MemSourceBatchOp(
        [(a, b, float(w)) for (a, b), w in zip(pairs, weights)],
        "source string, target string, weight double")


def _two_cliques():
    """Two 4-cliques joined by one bridge edge."""
    left = ["a", "b", "c", "d"]
    right = ["e", "f", "g", "h"]
    pairs = []
    for grp in (left, right):
        for i in range(4):
            for j in range(i + 1, 4):
                pairs.append((grp[i], grp[j]))
    pairs.append(("d", "e"))
    return pairs


def test_pagerank_star():
    # hub receives links from all leaves → highest rank
    pairs = [("l1", "hub"), ("l2", "hub"), ("l3", "hub"), ("l4", "hub")]
    out = PageRankBatchOp().link_from(_edges(pairs)).collect()
    ranks = dict(zip(out.col("vertex"), out.col("value")))
    assert ranks["hub"] == max(ranks.values())
    assert sum(ranks.values()) == pytest.approx(1.0, abs=1e-3)


def test_connected_components():
    pairs = [("a", "b"), ("b", "c"), ("x", "y")]
    out = ConnectedComponentsBatchOp().link_from(_edges(pairs)).collect()
    comp = dict(zip(out.col("vertex"), out.col("value")))
    assert comp["a"] == comp["b"] == comp["c"]
    assert comp["x"] == comp["y"]
    assert comp["a"] != comp["x"]


def test_kcore_drops_pendant():
    pairs = _two_cliques() + [("h", "tail")]
    out = KCoreBatchOp(k=3).link_from(_edges(pairs)).collect()
    kept = set(out.col("source")) | set(out.col("target"))
    assert "tail" not in kept
    assert {"a", "b", "c", "d", "e", "f", "g", "h"} <= kept
    # the bridge d-e survives only if both ends have core degree >= 3 (they do)
    assert out.num_rows >= 12


def test_sssp_weighted():
    pairs = [("s", "a"), ("a", "t"), ("s", "t")]
    out = SingleSourceShortestPathBatchOp(sourcePoint="s", weightCol="weight") \
        .link_from(_edges(pairs, [1.0, 1.0, 5.0])).collect()
    dist = dict(zip(out.col("vertex"), out.col("value")))
    assert dist["s"] == 0.0
    assert dist["a"] == 1.0
    assert dist["t"] == 2.0          # through a, not the direct 5.0 edge


def test_louvain_and_modularity():
    edges = _edges(_two_cliques())
    comm_op = LouvainBatchOp().link_from(edges)
    comm = comm_op.collect()
    by_v = dict(zip(comm.col("vertex"), comm.col("value")))
    assert by_v["a"] == by_v["b"] == by_v["c"] == by_v["d"]
    assert by_v["e"] == by_v["f"] == by_v["g"] == by_v["h"]
    assert by_v["a"] != by_v["e"]
    q = ModularityCalBatchOp().link_from(_edges(_two_cliques()), comm_op) \
        .collect().col("modularity")[0]
    assert q > 0.3


def test_community_detection_label_propagation():
    out = CommunityDetectionClusterBatchOp().link_from(
        _edges(_two_cliques())).collect()
    by_v = dict(zip(out.col("vertex"), out.col("value")))
    # cliques end up internally consistent
    assert len({by_v[v] for v in "abcd"}) == 1
    assert len({by_v[v] for v in "efgh"}) == 1


def test_triangle_list_and_coefficients():
    pairs = [("a", "b"), ("b", "c"), ("a", "c"), ("c", "d")]
    out = TriangleListBatchOp().link_from(_edges(pairs)).collect()
    assert out.num_rows == 1
    assert set(out.rows().__iter__().__next__()) == {"a", "b", "c"}
    vc = VertexClusterCoefficientBatchOp().link_from(_edges(pairs)).collect()
    coef = dict(zip(vc.col("vertex"), vc.col("value")))
    assert coef["a"] == pytest.approx(1.0)     # a's 2 neighbors are connected
    assert coef["c"] == pytest.approx(1.0 / 3)  # 1 of 3 neighbor pairs
    assert coef["d"] == 0.0
    ec = EdgeClusterCoefficientBatchOp().link_from(_edges(pairs)).collect()
    cn = {(r[0], r[1]): r[2] for r in ec.rows()}
    assert cn[("a", "b")] == 1.0               # common neighbor c


def test_common_neighbors():
    pairs = [("u", "x"), ("v", "x"), ("u", "y"), ("v", "y"), ("u", "v")]
    out = CommonNeighborsBatchOp().link_from(_edges(pairs)).collect()
    row = {(r[0], r[1]): r for r in out.rows()}
    assert row[("u", "v")][3] == 2.0
    assert set(row[("u", "v")][2].split()) == {"x", "y"}


def test_multi_source_shortest_path():
    from alink_tpu.operator.batch import MultiSourceShortestPathBatchOp

    edges = [("a", "b"), ("b", "c"), ("c", "d"), ("x", "y"), ("y", "d")]
    t = MTable.from_rows(edges, "source string, target string")
    out = MultiSourceShortestPathBatchOp(
        sourcePoints=["a", "x"]).link_from(TableSourceBatchOp(t)).collect()
    d = {r[0]: (r[1], r[2]) for r in out.rows()}
    assert d["b"][0] == 1.0 and d["b"][1] == "a"
    assert d["y"][0] == 1.0 and d["y"][1] == "x"
    assert d["d"][0] == 2.0  # via x->y->d, closer than a->b->c->d


def test_tree_depth():
    from alink_tpu.operator.batch import TreeDepthBatchOp

    edges = [("r", "c1"), ("r", "c2"), ("c1", "g1"), ("r2", "z")]
    t = MTable.from_rows(edges, "source string, target string")
    out = TreeDepthBatchOp().link_from(TableSourceBatchOp(t)).collect()
    d = {r[0]: (r[1], r[2]) for r in out.rows()}
    assert d["r"] == ("r", 0) and d["g1"] == ("r", 2)
    assert d["z"] == ("r2", 1)


def test_vertex_neighbor_search():
    from alink_tpu.operator.batch import VertexNeighborSearchBatchOp

    edges = [("a", "b"), ("b", "c"), ("c", "d")]
    t = MTable.from_rows(edges, "source string, target string")
    out = VertexNeighborSearchBatchOp(
        sources=["a"], depth=2).link_from(TableSourceBatchOp(t)).collect()
    got = {(r[0], r[1]) for r in out.rows()}
    # within 2 hops of a: vertices {a,b,c}; induced edges a-b, b-c
    assert got == {("a", "b"), ("b", "c")}

"""Op catalog + docs generation tests (reference:
common/annotation/PublicOperatorUtils.java, GeneratePyOp.java)."""

import os

from alink_tpu.common.catalog import (
    generate_docs,
    list_operators,
    op_info,
    params_of,
    port_specs,
)


def test_catalog_lists_many_ops():
    ops = list_operators()
    assert len(ops["batch"]) > 200
    assert len(ops["stream"]) >= 8
    names = {c.__name__ for c in ops["batch"]}
    for expected in ("KMeansTrainBatchOp", "FpGrowthBatchOp",
                     "PageRankBatchOp", "ArimaBatchOp",
                     "OnnxModelPredictBatchOp"):
        assert expected in names


def test_port_specs_and_params():
    from alink_tpu.operator.batch import (CsvSourceBatchOp,
                                          KMeansPredictBatchOp,
                                          KMeansTrainBatchOp)

    assert port_specs(CsvSourceBatchOp)["inputs"] == []
    assert port_specs(KMeansTrainBatchOp)["outputs"] == ["MODEL"]
    assert port_specs(KMeansPredictBatchOp)["inputs"] == ["MODEL", "DATA"]
    pnames = {p.name for p in params_of(KMeansTrainBatchOp)}
    assert {"k", "maxIter", "distanceType"} <= pnames
    info = op_info(KMeansTrainBatchOp)
    assert info["params"] and info["doc"]


def test_generate_docs(tmp_path):
    files = generate_docs(str(tmp_path))
    assert len(files) > 20
    stats = [f for f in files if f.endswith("statistics.md")]
    assert stats
    content = open(stats[0]).read()
    assert "CorrelationBatchOp" in content and "| param |" in content


def test_generate_stubs(tmp_path):
    import ast

    from alink_tpu.common.catalog import generate_stubs

    files = generate_stubs(str(tmp_path))
    assert len(files) == 2
    for f in files:
        src = open(f).read()
        ast.parse(src)                       # valid python syntax
        assert "__getattr__" in src          # incomplete-stub fallback
    batch = open([f for f in files if "batch" in f][0]).read()
    assert "class KMeansTrainBatchOp" in batch
    assert "k: Optional[int]" in batch


def test_generate_docs_cn(tmp_path):
    from alink_tpu.common.docs_cn import cn_title, generate_docs_cn

    files = generate_docs_cn(str(tmp_path))
    assert len(files) > 50
    content = open([f for f in files if f.endswith("clustering.md")][0],
                   encoding="utf-8").read()
    assert "K均值聚类 训练 (批)" in content
    assert "预测结果列" in content  # param rows carry CN descriptions
    assert cn_title("LogisticRegressionTrainBatchOp") == "逻辑回归 训练 (批)"

"""Auto-insights: automatic findings over a table.

Capability parity with the reference's insight engine (reference:
core/src/main/java/com/alibaba/alink/common/insights/AutoDiscovery.java —
5.5k LoC of correlation/breakdown/impact detectors feeding the WebUI).

Re-design: a compact detector suite over the columnar block — each finding
is a (type, columns, score, description) row, ranked by score. Detectors:
missing values, dominant category, high pairwise correlation, outlier-heavy
columns, low-variance columns."""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

from ...common.mtable import AlinkTypes, MTable, TableSchema
from ...common.params import ParamInfo
from ...mapper import HasSelectedCols
from .base import BatchOperator

_INSIGHT_SCHEMA = TableSchema(
    ["type", "columns", "score", "description"],
    [AlinkTypes.STRING, AlinkTypes.STRING, AlinkTypes.DOUBLE,
     AlinkTypes.STRING])


class AutoDiscoveryBatchOp(BatchOperator, HasSelectedCols):
    """(reference: common/insights/AutoDiscovery.java)"""

    TOP_N = ParamInfo("topN", int, default=20)

    _min_inputs = 1
    _max_inputs = 1

    def _execute_impl(self, t: MTable) -> MTable:
        findings: List[Tuple[str, str, float, str]] = []
        cols = list(self.get(HasSelectedCols.SELECTED_COLS) or t.names)
        numeric = [c for c in cols
                   if AlinkTypes.is_numeric(t.schema.type_of(c))]
        categorical = [c for c in cols
                       if t.schema.type_of(c) == AlinkTypes.STRING]
        n = max(t.num_rows, 1)

        for c in numeric:
            arr = np.asarray(t.col(c), np.float64)
            miss = float(np.isnan(arr).mean())
            if miss > 0.05:
                findings.append((
                    "missing_values", c, miss,
                    f"{c}: {miss:.1%} of values are missing"))
            ok = arr[~np.isnan(arr)]
            if ok.size > 1:
                std = ok.std()
                if std < 1e-12:
                    findings.append((
                        "constant_column", c, 1.0,
                        f"{c} is constant ({ok[0]:g})"))
                else:
                    z = np.abs(ok - ok.mean()) / std
                    frac_out = float((z > 3).mean())
                    if frac_out > 0.01:
                        findings.append((
                            "outliers", c, frac_out,
                            f"{c}: {frac_out:.1%} of values beyond 3 sigma"))

        for c in categorical:
            vals, counts = np.unique(
                np.asarray(t.col(c), object).astype(str), return_counts=True)
            top_frac = float(counts.max() / n)
            if len(vals) > 1 and top_frac > 0.8:
                findings.append((
                    "dominant_category", c, top_frac,
                    f"{c}: {vals[counts.argmax()]!r} covers "
                    f"{top_frac:.1%} of rows"))

        # breakdown + impact detectors (reference: AutoDiscovery.java's
        # BreakdownDetector/ImpactDetector — per-segment deltas and
        # top-segment contribution over (categorical, numeric) pairs)
        for c in categorical:
            seg_raw = np.asarray(t.col(c), object).astype(str)
            seg_vals_np, seg_inv = np.unique(seg_raw, return_inverse=True)
            seg_vals = [str(v) for v in seg_vals_np]
            if not (2 <= len(seg_vals) <= 50):
                continue
            for m in numeric:
                arr = np.asarray(t.col(m), np.float64)
                ok = ~np.isnan(arr)
                if ok.sum() < 10:
                    continue
                counts = np.bincount(seg_inv[ok], minlength=len(seg_vals))
                sums = np.bincount(seg_inv[ok], weights=arr[ok],
                                   minlength=len(seg_vals))
                overall_mean = arr[ok].mean()
                overall_std = arr[ok].std()
                with np.errstate(invalid="ignore", divide="ignore"):
                    means = sums / np.maximum(counts, 1)
                    # z-score of each segment mean vs the overall mean,
                    # scaled by the standard error of that segment
                    se = overall_std / np.sqrt(np.maximum(counts, 1))
                    z = np.abs(means - overall_mean) / np.maximum(se, 1e-12)
                big = (counts >= 5) & (z > 3.0)
                for si in np.flatnonzero(big):
                    delta = means[si] - overall_mean
                    findings.append((
                        "breakdown", f"{m} by {c}={seg_vals[si]}",
                        min(float(z[si]) / 10.0, 1.0),
                        f"{m} averages {means[si]:g} for {c}="
                        f"{seg_vals[si]!r} vs {overall_mean:g} overall "
                        f"({'+' if delta >= 0 else ''}{delta:g}, "
                        f"z={z[si]:.1f}, n={int(counts[si])})"))
                total = sums.sum()
                if abs(total) > 1e-12 and np.all(sums >= 0):
                    contrib = sums / total
                    si = int(np.argmax(contrib))
                    if contrib[si] > 0.5 and len(seg_vals) > 2:
                        findings.append((
                            "impact", f"{m} from {c}={seg_vals[si]}",
                            float(contrib[si]),
                            f"{c}={seg_vals[si]!r} contributes "
                            f"{contrib[si]:.1%} of total {m} "
                            f"across {len(seg_vals)} segments"))

        if len(numeric) >= 2:
            X = t.to_numeric_block(numeric, dtype=np.float64)
            ok_rows = ~np.isnan(X).any(axis=1)
            if ok_rows.sum() > 2:
                with np.errstate(invalid="ignore", divide="ignore"):
                    corr = np.corrcoef(X[ok_rows].T)
                for i in range(len(numeric)):
                    for j in range(i + 1, len(numeric)):
                        r = float(corr[i, j])
                        if abs(r) > 0.8:
                            findings.append((
                                "correlation",
                                f"{numeric[i]},{numeric[j]}", abs(r),
                                f"{numeric[i]} and {numeric[j]} correlate "
                                f"(r={r:.3f})"))

        findings.sort(key=lambda f: -f[2])
        findings = findings[:self.get(self.TOP_N)]
        if not findings:
            return MTable({k: np.asarray([], object) if i in (0, 1, 3)
                           else np.asarray([], np.float64)
                           for i, k in enumerate(_INSIGHT_SCHEMA.names)},
                          _INSIGHT_SCHEMA)
        return MTable.from_rows(findings, _INSIGHT_SCHEMA)

    def _out_schema(self, in_schema):
        return _INSIGHT_SCHEMA

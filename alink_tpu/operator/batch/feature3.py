"""Feature transforms: Binarizer, Bucketizer, MultiHot, TargetEncoder,
ExclusiveFeatureBundle, MultiStringIndexer, IndexToString.

Capability parity (reference: operator/batch/feature/BinarizerBatchOp.java,
BucketizerBatchOp.java, MultiHotTrainBatchOp.java / MultiHotPredictBatchOp
.java, TargetEncoderTrainBatchOp.java / TargetEncoderPredictBatchOp.java,
ExclusiveFeatureBundlePredictBatchOp.java, dataproc/
MultiStringIndexerTrainBatchOp.java / MultiStringIndexerPredictBatchOp.java,
dataproc/IndexToStringPredictBatchOp.java).
"""

from __future__ import annotations

import json
from typing import Dict, List

import numpy as np

from ...common.exceptions import (
    AkIllegalArgumentException,
    AkIllegalDataException,
)
from ...common.linalg import SparseVector, parse_vector
from ...common.model import model_to_table, table_to_model
from ...common.mtable import AlinkTypes, MTable, TableSchema
from ...common.params import InValidator, MinValidator, ParamInfo
from ...mapper import (
    HasOutputCol,
    HasOutputCols,
    HasReservedCols,
    HasSelectedCol,
    HasSelectedCols,
    Mapper,
    ModelMapper,
    SISOMapper,
)
from .base import BatchOperator
from .dataproc import (
    StringIndexerModelMapper,
    StringIndexerPredictBatchOp,
    StringIndexerTrainBatchOp,
)
from .utils import MapBatchOp, ModelMapBatchOp, ModelTrainOpMixin


class BinarizerMapper(SISOMapper):
    """Numeric → 0/1 by threshold (reference:
    common/feature/BinarizerMapper.java)."""

    THRESHOLD = ParamInfo("threshold", float, default=0.0)

    def map_column(self, values, type_tag):
        thr = float(self.get(self.THRESHOLD))
        a = np.asarray(values, np.float64)
        return (a > thr).astype(np.float64), AlinkTypes.DOUBLE


class BinarizerBatchOp(MapBatchOp, HasSelectedCol, HasOutputCol,
                       HasReservedCols):
    """(reference: operator/batch/feature/BinarizerBatchOp.java)"""

    mapper_cls = BinarizerMapper
    THRESHOLD = BinarizerMapper.THRESHOLD


class BucketizerMapper(Mapper, HasSelectedCols, HasOutputCols,
                       HasReservedCols):
    """Numeric → bucket index by explicit cut points (reference:
    common/feature/BucketizerMapper.java; cutsArray per column)."""

    CUTS_ARRAY = ParamInfo("cutsArray", list, optional=False,
                           desc="list of cut-point lists, one per column")

    def _io_cols(self):
        in_cols = list(self.get(HasSelectedCols.SELECTED_COLS))
        out_cols = list(self.get(HasOutputCols.OUTPUT_COLS) or in_cols)
        return in_cols, out_cols

    def output_schema(self, input_schema):
        in_cols, out_cols = self._io_cols()
        names, types = list(input_schema.names), list(input_schema.types)
        for oc in out_cols:
            if oc in names:
                types[names.index(oc)] = AlinkTypes.LONG
            else:
                names.append(oc)
                types.append(AlinkTypes.LONG)
        return TableSchema(names, types)

    def map_table(self, t: MTable) -> MTable:
        in_cols, out_cols = self._io_cols()
        cuts = self.get(self.CUTS_ARRAY)
        if len(cuts) != len(in_cols):
            raise AkIllegalArgumentException(
                f"cutsArray has {len(cuts)} entries for {len(in_cols)} cols")
        out = t
        for ic, oc, cut in zip(in_cols, out_cols, cuts):
            edges = np.asarray(sorted(float(c) for c in cut), np.float64)
            idx = np.searchsorted(edges, np.asarray(t.col(ic), np.float64),
                                  side="right")
            out = out.with_column(oc, idx.astype(np.int64), AlinkTypes.LONG)
        return out


class BucketizerBatchOp(MapBatchOp, HasSelectedCols, HasOutputCols,
                        HasReservedCols):
    """(reference: operator/batch/feature/BucketizerBatchOp.java)"""

    mapper_cls = BucketizerMapper
    CUTS_ARRAY = BucketizerMapper.CUTS_ARRAY


# ---------------------------------------------------------------------------
# MultiHot — delimiter-separated token sets → multi-hot sparse vector
# ---------------------------------------------------------------------------


class MultiHotTrainBatchOp(ModelTrainOpMixin, BatchOperator, HasSelectedCols):
    """Collect the token vocabulary of delimiter-separated categorical
    columns (reference: operator/batch/feature/MultiHotTrainBatchOp.java)."""

    DELIMITER = ParamInfo("delimiter", str, default=",")

    _min_inputs = 1
    _max_inputs = 1

    def _execute_impl(self, t: MTable) -> MTable:
        cols = list(self.get(HasSelectedCols.SELECTED_COLS) or t.names)
        delim = self.get(self.DELIMITER)
        vocab: Dict[str, List[str]] = {}
        for c in cols:
            toks = set()
            for v in t.col(c):
                if v is None:
                    continue
                for tok in str(v).split(delim):
                    tok = tok.strip()
                    if tok:
                        toks.add(tok)
            vocab[c] = sorted(toks)
        meta = {"modelName": "MultiHotModel", "selectedCols": cols,
                "delimiter": delim, "vocab": vocab}
        return model_to_table(meta, {})

    def _static_meta_keys(self, in_schema):
        return {"modelName": "MultiHotModel"}


class MultiHotModelMapper(ModelMapper, HasReservedCols, HasOutputCol):
    """Each selected column's token set → one concatenated multi-hot sparse
    vector (reference: common/feature/MultiHotModelMapper.java)."""

    def load_model(self, model: MTable):
        self.meta, _ = table_to_model(model)
        self.luts = {c: {tok: i for i, tok in enumerate(toks)}
                     for c, toks in self.meta["vocab"].items()}
        self.offsets = {}
        off = 0
        for c in self.meta["selectedCols"]:
            self.offsets[c] = off
            off += len(self.luts[c]) + 1  # +1 unseen slot per column
        self.dim = off
        return self

    def output_schema(self, input_schema):
        out = self.get(HasOutputCol.OUTPUT_COL) or "multihot"
        return self._append_result_schema(
            input_schema, [out], [AlinkTypes.SPARSE_VECTOR])

    def map_table(self, t: MTable) -> MTable:
        delim = self.meta["delimiter"]
        cols = self.meta["selectedCols"]
        n = t.num_rows
        vecs = np.empty(n, object)
        col_vals = {c: t.col(c) for c in cols}
        for i in range(n):
            idx = set()
            for c in cols:
                v = col_vals[c][i]
                lut, off = self.luts[c], self.offsets[c]
                if v is None:
                    continue
                for tok in str(v).split(delim):
                    tok = tok.strip()
                    if not tok:
                        continue
                    idx.add(off + lut.get(tok, len(lut)))
            sidx = np.asarray(sorted(idx), np.int64)
            vecs[i] = SparseVector(self.dim, sidx,
                                   np.ones(len(sidx), np.float64))
        out = self.get(HasOutputCol.OUTPUT_COL) or "multihot"
        return self._append_result(
            t, {out: vecs}, {out: AlinkTypes.SPARSE_VECTOR})


class MultiHotPredictBatchOp(ModelMapBatchOp, HasReservedCols, HasOutputCol):
    """(reference: operator/batch/feature/MultiHotPredictBatchOp.java)"""

    mapper_cls = MultiHotModelMapper


# ---------------------------------------------------------------------------
# TargetEncoder — category → smoothed mean label
# ---------------------------------------------------------------------------


class TargetEncoderTrainBatchOp(ModelTrainOpMixin, BatchOperator,
                                HasSelectedCols):
    """Per-category smoothed target means (reference:
    operator/batch/feature/TargetEncoderTrainBatchOp.java; the smoothing
    blends the category mean with the global prior by category count)."""

    LABEL_COL = ParamInfo("labelCol", str, optional=False)
    POSITIVE_LABEL_VALUE_STRING = ParamInfo(
        "positiveLabelValueString", str, default=None,
        desc="treat label as binary with this positive value")
    SMOOTHING = ParamInfo("smoothing", float, default=0.0,
                          desc="pseudo-count blending toward the global mean")

    _min_inputs = 1
    _max_inputs = 1

    def _execute_impl(self, t: MTable) -> MTable:
        label_col = self.get(self.LABEL_COL)
        cols = list(self.get(HasSelectedCols.SELECTED_COLS) or
                    [c for c in t.names if c != label_col])
        pos = self.get(self.POSITIVE_LABEL_VALUE_STRING)
        y_raw = t.col(label_col)
        if pos is not None:
            y = np.asarray([1.0 if str(v) == pos else 0.0 for v in y_raw])
        else:
            y = np.asarray(y_raw, np.float64)
        prior = float(y.mean())
        s = float(self.get(self.SMOOTHING))
        maps: Dict[str, Dict[str, float]] = {}
        for c in cols:
            vals = np.asarray(t.col(c), object).astype(str)
            enc: Dict[str, float] = {}
            for cat in np.unique(vals):
                mask = vals == cat
                cnt = float(mask.sum())
                enc[str(cat)] = (y[mask].sum() + s * prior) / (cnt + s)
            maps[c] = enc
        meta = {"modelName": "TargetEncoderModel", "selectedCols": cols,
                "prior": prior, "encodings": maps}
        return model_to_table(meta, {})

    def _static_meta_keys(self, in_schema):
        return {"modelName": "TargetEncoderModel"}


class TargetEncoderModelMapper(ModelMapper, HasReservedCols, HasOutputCols):
    def load_model(self, model: MTable):
        self.meta, _ = table_to_model(model)
        return self

    def _io_cols(self):
        in_cols = self.meta["selectedCols"]
        out_cols = list(self.get(HasOutputCols.OUTPUT_COLS) or
                        [f"{c}_te" for c in in_cols])
        return in_cols, out_cols

    def output_schema(self, input_schema):
        _, out_cols = self._io_cols()
        return self._append_result_schema(
            input_schema, out_cols, [AlinkTypes.DOUBLE] * len(out_cols))

    def map_table(self, t: MTable) -> MTable:
        in_cols, out_cols = self._io_cols()
        prior = self.meta["prior"]
        add, types = {}, {}
        for ic, oc in zip(in_cols, out_cols):
            enc = self.meta["encodings"][ic]
            vals = np.asarray(t.col(ic), object).astype(str)
            add[oc] = np.asarray([enc.get(v, prior) for v in vals],
                                 np.float64)
            types[oc] = AlinkTypes.DOUBLE
        return self._append_result(t, add, types)


class TargetEncoderPredictBatchOp(ModelMapBatchOp, HasReservedCols,
                                  HasOutputCols):
    """(reference: operator/batch/feature/TargetEncoderPredictBatchOp.java)"""

    mapper_cls = TargetEncoderModelMapper


# ---------------------------------------------------------------------------
# ExclusiveFeatureBundle — LightGBM-style EFB over sparse vectors
# ---------------------------------------------------------------------------


class ExclusiveFeatureBundleTrainBatchOp(ModelTrainOpMixin, BatchOperator):
    """Greedily bundle (almost) mutually-exclusive sparse dims so each bundle
    becomes ONE dense feature (reference: operator/batch/feature/
    ExclusiveFeatureBundlePredictBatchOp.java family — the EFB trick)."""

    SELECTED_COL = ParamInfo("selectedCol", str, optional=False,
                             aliases=("vectorCol",))

    _min_inputs = 1
    _max_inputs = 1

    def _execute_impl(self, t: MTable) -> MTable:
        vec_col = self.get(self.SELECTED_COL)
        vecs = [parse_vector(v) for v in t.col(vec_col)]
        dim = max((v.size() for v in vecs), default=0)
        nz: List[set] = [set() for _ in range(dim)]
        for row, v in enumerate(vecs):
            sv = v if isinstance(v, SparseVector) else None
            idxs = (sv.indices if sv is not None
                    else np.nonzero(v.to_dense().data)[0])
            for j in idxs:
                nz[int(j)].add(row)
        bundles: List[List[int]] = []
        bundle_rows: List[set] = []
        for j in range(dim):
            placed = False
            for b, rows in enumerate(bundle_rows):
                if not (rows & nz[j]):
                    bundles[b].append(j)
                    rows |= nz[j]
                    placed = True
                    break
            if not placed:
                bundles.append([j])
                bundle_rows.append(set(nz[j]))
        meta = {"modelName": "ExclusiveFeatureBundleModel",
                "vectorCol": vec_col, "dim": dim, "bundles": bundles}
        return model_to_table(meta, {})

    def _static_meta_keys(self, in_schema):
        return {"modelName": "ExclusiveFeatureBundleModel"}


class ExclusiveFeatureBundleModelMapper(ModelMapper, HasReservedCols,
                                        HasOutputCol):
    def load_model(self, model: MTable):
        self.meta, _ = table_to_model(model)
        self.slot = np.zeros(self.meta["dim"], np.int64)
        self.local = np.zeros(self.meta["dim"], np.int64)
        for b, dims in enumerate(self.meta["bundles"]):
            for k, j in enumerate(dims):
                self.slot[j] = b
                self.local[j] = k + 1  # 0 = empty
        return self

    def output_schema(self, input_schema):
        out = self.get(HasOutputCol.OUTPUT_COL) or "efb"
        return self._append_result_schema(
            input_schema, [out], [AlinkTypes.DENSE_VECTOR])

    def map_table(self, t: MTable) -> MTable:
        from ...common.linalg import DenseVector

        vec_col = self.meta["vectorCol"]
        nb = len(self.meta["bundles"])
        out_vecs = np.empty(t.num_rows, object)
        for i, v in enumerate(t.col(vec_col)):
            sv = parse_vector(v)
            dense = np.zeros(nb, np.float64)
            if isinstance(sv, SparseVector):
                for j in sv.indices:
                    dense[self.slot[int(j)]] = float(self.local[int(j)])
            else:
                for j in np.nonzero(sv.to_dense().data)[0]:
                    dense[self.slot[int(j)]] = float(self.local[int(j)])
            out_vecs[i] = DenseVector(dense)
        out = self.get(HasOutputCol.OUTPUT_COL) or "efb"
        return self._append_result(
            t, {out: out_vecs}, {out: AlinkTypes.DENSE_VECTOR})


class ExclusiveFeatureBundlePredictBatchOp(ModelMapBatchOp, HasReservedCols,
                                           HasOutputCol):
    """(reference: operator/batch/feature/
    ExclusiveFeatureBundlePredictBatchOp.java)"""

    mapper_cls = ExclusiveFeatureBundleModelMapper


# ---------------------------------------------------------------------------
# MultiStringIndexer / IndexToString
# ---------------------------------------------------------------------------


class MultiStringIndexerTrainBatchOp(StringIndexerTrainBatchOp):
    """Multi-column token indexing in one model — this engine's
    StringIndexer is already multi-column, so the Multi variant IS the
    base trainer (reference: dataproc/MultiStringIndexerTrainBatchOp.java)."""


class MultiStringIndexerPredictBatchOp(StringIndexerPredictBatchOp):
    """(reference: dataproc/MultiStringIndexerPredictBatchOp.java)"""


class IndexToStringModelMapper(ModelMapper, HasSelectedCol, HasOutputCol,
                               HasReservedCols):
    """Inverse of StringIndexer: LONG id → original token using the SAME
    StringIndexer model (reference: dataproc/
    IndexToStringPredictBatchOp.java)."""

    MODEL_NAME_COL = ParamInfo("modelCol", str, default=None,
                               desc="model column to invert; default first")

    def load_model(self, model: MTable):
        self.meta, _ = table_to_model(model)
        return self

    def output_schema(self, input_schema):
        out = (self.get(HasOutputCol.OUTPUT_COL) or
               self.get(HasSelectedCol.SELECTED_COL))
        names, types = list(input_schema.names), list(input_schema.types)
        if out in names:
            types[names.index(out)] = AlinkTypes.STRING
        else:
            names.append(out)
            types.append(AlinkTypes.STRING)
        return TableSchema(names, types)

    def map_table(self, t: MTable) -> MTable:
        sel = self.get(HasSelectedCol.SELECTED_COL)
        out = self.get(HasOutputCol.OUTPUT_COL) or sel
        model_col = (self.get(self.MODEL_NAME_COL) or
                     self.meta["selectedCols"][0])
        toks = self.meta["tokenMaps"][model_col]
        ids = np.asarray(t.col(sel), np.int64)
        vals = np.asarray(
            [toks[i] if 0 <= i < len(toks) else None for i in ids], object)
        return t.with_column(out, vals, AlinkTypes.STRING)


class IndexToStringPredictBatchOp(ModelMapBatchOp, HasSelectedCol,
                                  HasOutputCol, HasReservedCols):
    """(reference: operator/batch/dataproc/IndexToStringPredictBatchOp.java)"""

    mapper_cls = IndexToStringModelMapper
    MODEL_NAME_COL = IndexToStringModelMapper.MODEL_NAME_COL

"""Stream source/sink breadth: file formats as micro-batch streams.

Capability parity with the reference's stream IO ops (reference:
operator/stream/source/TextSourceStreamOp.java, TsvSourceStreamOp.java,
LibSvmSourceStreamOp.java, AkSourceStreamOp.java and the sink family
operator/stream/sink/CsvSinkStreamOp.java, AkSinkStreamOp.java,
TsvSinkStreamOp.java, Export2FileSinkStreamOp.java — each wraps the batch
reader/writer behind Flink's streaming runtime).

Re-design: each source delegates to its batch twin's reader and yields
fixed-size chunks; sinks append per chunk. Export2FileSinkStreamOp writes
each micro-batch as its own timestamped part file (the reference's
per-checkpoint file rolling)."""

from __future__ import annotations

import time
from typing import Iterator

from ...common.mtable import MTable, TableSchema
from ...common.params import InValidator, ParamInfo
from .base import StreamOperator


def _chunked(table: MTable, chunk: int) -> Iterator[MTable]:
    for s in range(0, table.num_rows, chunk):
        yield table.slice(s, min(s + chunk, table.num_rows))


class _BatchReaderSource(StreamOperator):
    """Read via the batch twin once, emit micro-batches."""

    CHUNK_SIZE = ParamInfo("chunkSize", int, default=1024)

    _max_inputs = 0
    _batch_cls: type = None

    def _stream_impl(self) -> Iterator[MTable]:
        inner = self._batch_cls(self.get_params().clone())
        yield from _chunked(inner._execute_impl(),
                            max(1, self.get(self.CHUNK_SIZE)))

    def _out_schema(self) -> TableSchema:
        return self._batch_cls(self.get_params().clone())._out_schema()


def _source(name: str, batch_cls: type, doc: str) -> type:
    ns = {"_batch_cls": batch_cls, "__doc__": doc}
    for pname in dir(batch_cls):
        p = getattr(batch_cls, pname)
        if pname.isupper() and hasattr(p, "name"):
            ns[pname] = p
    return type(name, (_BatchReaderSource,), ns)


from ..batch.base import AkSourceBatchOp, CsvSourceBatchOp  # noqa: E402
from ..batch.sources import (  # noqa: E402
    LibSvmSourceBatchOp,
    ParquetSourceBatchOp,
    TextSourceBatchOp,
    TFRecordSourceBatchOp,
    TsvSourceBatchOp,
)

TextSourceStreamOp = _source(
    "TextSourceStreamOp", TextSourceBatchOp,
    "(reference: TextSourceStreamOp.java)")
TsvSourceStreamOp = _source(
    "TsvSourceStreamOp", TsvSourceBatchOp,
    "(reference: TsvSourceStreamOp.java)")
LibSvmSourceStreamOp = _source(
    "LibSvmSourceStreamOp", LibSvmSourceBatchOp,
    "(reference: LibSvmSourceStreamOp.java)")
AkSourceStreamOp = _source(
    "AkSourceStreamOp", AkSourceBatchOp,
    "(reference: AkSourceStreamOp.java)")
ParquetSourceStreamOp = _source(
    "ParquetSourceStreamOp", ParquetSourceBatchOp,
    "(reference: ParquetSourceStreamOp.java)")
TFRecordSourceStreamOp = _source(
    "TFRecordSourceStreamOp", TFRecordSourceBatchOp,
    "(reference: TFRecordDatasetSourceStreamOp.java)")


class CsvSinkStreamOp(StreamOperator):
    """Append every chunk to one CSV file (reference:
    CsvSinkStreamOp.java)."""

    # file-writing pass-through with cross-chunk generator state
    # (open/truncating handle or full-stream buffer): a crash-restart
    # would truncate or drop pre-crash output, so the recovery runtime
    # refuses it until it speaks the _txn_* sink protocol
    _stateful_unhooked = True

    FILE_PATH = ParamInfo("filePath", str, optional=False)
    FIELD_DELIMITER = ParamInfo("fieldDelimiter", str, default=",")

    _min_inputs = 1
    _max_inputs = 1

    def _stream_impl(self, it: Iterator[MTable]) -> Iterator[MTable]:
        from ...io.filesystem import file_open

        path = self.get(self.FILE_PATH)
        delim = self.get(self.FIELD_DELIMITER)
        with file_open(path, "w") as f:
            for chunk in it:
                chunk.to_dataframe().to_csv(f, sep=delim, index=False,
                                            header=False)
                yield chunk

    def _out_schema(self, in_schema: TableSchema) -> TableSchema:
        return in_schema


class AkSinkStreamOp(StreamOperator):
    """Collect the stream and land ONE .ak file at the end (reference:
    AkSinkStreamOp.java — the bounded-stream sink)."""

    # file-writing pass-through with cross-chunk generator state
    # (open/truncating handle or full-stream buffer): a crash-restart
    # would truncate or drop pre-crash output, so the recovery runtime
    # refuses it until it speaks the _txn_* sink protocol
    _stateful_unhooked = True

    FILE_PATH = ParamInfo("filePath", str, optional=False)

    _min_inputs = 1
    _max_inputs = 1

    def _stream_impl(self, it: Iterator[MTable]) -> Iterator[MTable]:
        from ...io.ak import write_ak

        chunks = []
        for chunk in it:
            chunks.append(chunk)
            yield chunk
        if chunks:
            write_ak(self.get(self.FILE_PATH), MTable.concat(chunks))

    def _out_schema(self, in_schema: TableSchema) -> TableSchema:
        return in_schema


class Export2FileSinkStreamOp(StreamOperator):
    """Each micro-batch rolls into its OWN timestamped part file under a
    directory (reference: Export2FileSinkStreamOp.java — time-rolling file
    export; format ak or csv)."""

    # file-writing pass-through with cross-chunk generator state
    # (open/truncating handle or full-stream buffer): a crash-restart
    # would truncate or drop pre-crash output, so the recovery runtime
    # refuses it until it speaks the _txn_* sink protocol
    _stateful_unhooked = True

    FILE_PATH = ParamInfo("filePath", str, optional=False,
                          desc="output DIRECTORY")
    FORMAT = ParamInfo("format", str, default="AK",
                       validator=InValidator("AK", "CSV"))

    _min_inputs = 1
    _max_inputs = 1

    def _stream_impl(self, it: Iterator[MTable]) -> Iterator[MTable]:
        from ...io.ak import write_ak
        from ...io.filesystem import file_open, get_file_system

        root = self.get(self.FILE_PATH)
        fs = get_file_system(root)
        fs.makedirs(root)
        fmt = self.get(self.FORMAT)
        part = 0
        for chunk in it:
            ts = int(time.time() * 1000)
            if fmt == "AK":
                fname = fs.join(root, f"part-{ts}-{part:05d}.ak")
                write_ak(fname, chunk)
            else:
                fname = fs.join(root, f"part-{ts}-{part:05d}.csv")
                with file_open(fname, "w") as f:
                    chunk.to_dataframe().to_csv(f, index=False, header=False)
            part += 1
            yield chunk

    def _out_schema(self, in_schema: TableSchema) -> TableSchema:
        return in_schema


class TsvSinkStreamOp(StreamOperator):
    """(reference: TsvSinkStreamOp.java)"""

    # file-writing pass-through with cross-chunk generator state
    # (open/truncating handle or full-stream buffer): a crash-restart
    # would truncate or drop pre-crash output, so the recovery runtime
    # refuses it until it speaks the _txn_* sink protocol
    _stateful_unhooked = True

    FILE_PATH = ParamInfo("filePath", str, optional=False)

    _min_inputs = 1
    _max_inputs = 1

    def _stream_impl(self, it: Iterator[MTable]) -> Iterator[MTable]:
        from ...io.filesystem import file_open

        with file_open(self.get(self.FILE_PATH), "w") as f:
            for chunk in it:
                for row in chunk.rows():
                    f.write("\t".join("" if v is None else str(v)
                                      for v in row) + "\n")
                yield chunk

    def _out_schema(self, in_schema: TableSchema) -> TableSchema:
        return in_schema

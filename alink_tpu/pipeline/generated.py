"""Reflective closure of the reference's pipeline class surface.

Capability parity with the generated pipeline layer (reference:
core/src/main/java/com/alibaba/alink/pipeline/**/*.java — ~326 thin
Trainer/Transformer/Model wrappers over the batch ops, produced there by
codegen). Here the same surface is produced at import time from four spec
tables (reference pipeline name -> our operator names): an Estimator gets
the train/predict pair, a Model the predict op, a Transformer its map op,
and a Recommender its recomm op. Params mirror the underlying ops'
ParamInfos so the fluent setters work identically, and every class lands
in STAGE_REGISTRY for pipeline-model persistence.

Hand-written stages in estimators.py take precedence; only names absent
there are generated.
"""

from __future__ import annotations

from typing import Dict, Type

from ..common.params import ParamInfo
from ..operator import batch as _B
from .base import EstimatorBase, ModelBase, TransformerBase

__all__ = []  # filled by the factories below


# -- spec tables (reference pipeline name -> operator class names) -----------

ESTIMATORS: Dict[str, tuple] = {
    'AutoCross': ('AutoCrossTrainBatchOp', 'AutoCrossPredictBatchOp', 'AutoCrossModel'),
    'AutoCrossAlgo': ('AutoCrossTrainBatchOp', 'AutoCrossPredictBatchOp', 'AutoCrossAlgoModel'),
    'BertTextClassifier': ('BertTextClassifierTrainBatchOp', 'BertTextClassifierPredictBatchOp', 'BertTextClassifierModel'),
    'BertTextPairClassifier': ('BertTextPairClassifierTrainBatchOp', 'BertTextPairClassifierPredictBatchOp', 'BertTextPairClassifierModel'),
    'BertTextPairRegressor': ('BertTextPairRegressorTrainBatchOp', 'BertTextPairRegressorPredictBatchOp', 'BertTextPairRegressorModel'),
    'BertTextRegressor': ('BertTextRegressorTrainBatchOp', 'BertTextRegressorPredictBatchOp', 'BertTextRegressorModel'),
    'C45': ('C45TrainBatchOp', 'C45PredictBatchOp', 'C45Model'),
    'C45Encoder': ('C45EncoderTrainBatchOp', 'TreeModelEncoderBatchOp', 'C45EncoderModel'),
    'Cart': ('CartTrainBatchOp', 'CartPredictBatchOp', 'CartModel'),
    'CartEncoder': ('CartEncoderTrainBatchOp', 'TreeModelEncoderBatchOp', 'CartEncoderModel'),
    'CartReg': ('CartRegTrainBatchOp', 'CartRegPredictBatchOp', 'CartRegModel'),
    'CartRegEncoder': ('CartRegEncoderTrainBatchOp', 'TreeModelEncoderBatchOp', 'CartRegEncoderModel'),
    'CrossCandidateSelector': ('CrossCandidateSelectorTrainBatchOp', 'CrossCandidateSelectorPredictBatchOp', 'CrossCandidateSelectorModel'),
    'CrossFeature': ('CrossFeatureTrainBatchOp', 'CrossFeaturePredictBatchOp', 'CrossFeatureModel'),
    'DecisionTreeEncoder': ('DecisionTreeEncoderTrainBatchOp', 'TreeModelEncoderBatchOp', 'DecisionTreeEncoderModel'),
    'DecisionTreeRegEncoder': ('DecisionTreeRegEncoderTrainBatchOp', 'TreeModelEncoderBatchOp', 'DecisionTreeRegEncoderModel'),
    'DecisionTreeRegressor': ('DecisionTreeRegTrainBatchOp', 'DecisionTreeRegPredictBatchOp', 'DecisionTreeRegressionModel'),
    'DocCountVectorizer': ('DocCountVectorizerTrainBatchOp', 'DocCountVectorizerPredictBatchOp', 'DocCountVectorizerModel'),
    'DocHashCountVectorizer': ('DocHashCountVectorizerTrainBatchOp', 'DocHashCountVectorizerPredictBatchOp', 'DocHashCountVectorizerModel'),
    'EqualWidthDiscretizer': ('EqualWidthDiscretizerTrainBatchOp', 'EqualWidthDiscretizerPredictBatchOp', 'EqualWidthDiscretizerModel'),
    'ExclusiveFeatureBundle': ('ExclusiveFeatureBundleTrainBatchOp', 'ExclusiveFeatureBundlePredictBatchOp', 'ExclusiveFeatureBundleModel'),
    'GbdtEncoder': ('GbdtEncoderTrainBatchOp', 'GbdtEncoderPredictBatchOp', 'GbdtEncoderModel'),
    'GbdtRegEncoder': ('GbdtRegEncoderTrainBatchOp', 'TreeModelEncoderBatchOp', 'GbdtRegEncoderModel'),
    'GeoKMeans': ('GeoKMeansTrainBatchOp', 'GeoKMeansPredictBatchOp', 'GeoKMeansModel'),
    'IForestModelOutlier': ('IForestModelOutlierTrainBatchOp', 'IForestModelOutlierPredictBatchOp', 'IForestModelOutlierModel'),
    'Id3': ('Id3TrainBatchOp', 'Id3PredictBatchOp', 'Id3Model'),
    'Id3Encoder': ('Id3EncoderTrainBatchOp', 'TreeModelEncoderBatchOp', 'Id3EncoderModel'),
    'KModes': ('KModesTrainBatchOp', 'KModesPredictBatchOp', 'KModesModel'),
    'KerasSequentialClassifier': ('KerasSequentialClassifierTrainBatchOp', 'KerasSequentialClassifierPredictBatchOp', 'KerasSequentialClassifierModel'),
    'KerasSequentialRegressor': ('KerasSequentialRegressorTrainBatchOp', 'KerasSequentialRegressorPredictBatchOp', 'KerasSequentialRegressorModel'),
    'LassoRegression': ('LassoRegTrainBatchOp', 'LassoRegPredictBatchOp', 'LassoRegressionModel'),
    'LinearRegStepwise': ('LinearRegStepwiseTrainBatchOp', 'LinearRegStepwisePredictBatchOp', 'LinearRegStepwiseModel'),
    'MaxAbsScaler': ('MaxAbsScalerTrainBatchOp', 'MaxAbsScalerPredictBatchOp', 'MaxAbsScalerModel'),
    'MultiHotEncoder': ('MultiHotTrainBatchOp', 'MultiHotPredictBatchOp', 'MultiHotEncoderModel'),
    'MultiStringIndexer': ('MultiStringIndexerTrainBatchOp', 'MultiStringIndexerPredictBatchOp', 'MultiStringIndexerModel'),
    'NaiveBayesTextClassifier': ('NaiveBayesTextTrainBatchOp', 'NaiveBayesTextPredictBatchOp', 'NaiveBayesTextModel'),
    'OcsvmModelOutlier': ('OcsvmModelOutlierTrainBatchOp', 'OcsvmModelOutlierPredictBatchOp', 'OcsvmModelOutlierModel'),
    'OneVsRest': ('OneVsRestTrainBatchOp', 'OneVsRestPredictBatchOp', 'OneVsRestModel'),
    'RandomForestEncoder': ('RandomForestEncoderTrainBatchOp', 'TreeModelEncoderBatchOp', 'RandomForestEncoderModel'),
    'RandomForestRegEncoder': ('RandomForestRegEncoderTrainBatchOp', 'TreeModelEncoderBatchOp', 'RandomForestRegEncoderModel'),
    'RandomForestRegressor': ('RandomForestRegTrainBatchOp', 'RandomForestRegPredictBatchOp', 'RandomForestRegressionModel'),
    'RidgeRegression': ('RidgeRegTrainBatchOp', 'RidgeRegPredictBatchOp', 'RidgeRegressionModel'),
    'StringApproxNearestNeighbor': ('StringApproxNearestNeighborTrainBatchOp', 'StringApproxNearestNeighborPredictBatchOp', 'StringApproxNearestNeighborModel'),
    'StringNearestNeighbor': ('StringNearestNeighborTrainBatchOp', 'StringNearestNeighborPredictBatchOp', 'StringNearestNeighborModel'),
    'TF2TableModelTrainer': ('TF2TableModelTrainBatchOp', 'TFTableModelPredictBatchOp', 'TF2TableModelTrainerModel'),
    'TFTableModelTrainer': ('TFTableModelTrainBatchOp', 'TFTableModelPredictBatchOp', 'TFTableModelTrainerModel'),
    'TargetEncoder': ('TargetEncoderTrainBatchOp', 'TargetEncoderPredictBatchOp', 'TargetEncoderModel'),
    'TextApproxNearestNeighbor': ('TextApproxNearestNeighborTrainBatchOp', 'TextApproxNearestNeighborPredictBatchOp', 'TextApproxNearestNeighborModel'),
    'TextNearestNeighbor': ('TextNearestNeighborTrainBatchOp', 'TextNearestNeighborPredictBatchOp', 'TextNearestNeighborModel'),
    'VectorApproxNearestNeighbor': ('VectorApproxNearestNeighborTrainBatchOp', 'VectorApproxNearestNeighborPredictBatchOp', 'VectorApproxNearestNeighborModel'),
    'VectorImputer': ('VectorImputerTrainBatchOp', 'VectorImputerPredictBatchOp', 'VectorImputerModel'),
    'VectorMaxAbsScaler': ('VectorMaxAbsScalerTrainBatchOp', 'VectorMaxAbsScalerPredictBatchOp', 'VectorMaxAbsScalerModel'),
    'VectorMinMaxScaler': ('VectorMinMaxScalerTrainBatchOp', 'VectorMinMaxScalerPredictBatchOp', 'VectorMinMaxScalerModel'),
    'VectorNearestNeighbor': ('VectorNearestNeighborTrainBatchOp', 'VectorNearestNeighborPredictBatchOp', 'VectorNearestNeighborModel'),
    'VectorStandardScaler': ('VectorStandardScalerTrainBatchOp', 'VectorStandardScalerPredictBatchOp', 'VectorStandardScalerModel'),
    'XGBoostClassifier': ('XGBoostTrainBatchOp', 'XGBoostPredictBatchOp', 'XGBoostClassificationModel'),
    'XGBoostRegressor': ('XGBoostRegTrainBatchOp', 'XGBoostRegPredictBatchOp', 'XGBoostRegressionModel'),
}

MODELS: Dict[str, str] = {
    'LookupRecentDaysModel': 'LookupRecentDaysBatchOp',
    'IndexToString': 'IndexToStringPredictBatchOp',
    'TFTableModelPredictor': 'TFTableModelPredictBatchOp',
    'AggLookup': 'AggLookupBatchOp',
    'AutoCrossAlgoModel': 'AutoCrossPredictBatchOp',
    'AutoCrossModel': 'AutoCrossPredictBatchOp',
    'BertClassificationModel': 'BertTextClassifierPredictBatchOp',
    'BertRegressionModel': 'BertTextRegressorPredictBatchOp',
    'BertTextEmbedding': 'BertTextEmbeddingBatchOp',
    'C45EncoderModel': 'TreeModelEncoderBatchOp',
    'C45Model': 'C45PredictBatchOp',
    'CartEncoderModel': 'TreeModelEncoderBatchOp',
    'CartModel': 'CartPredictBatchOp',
    'CartRegEncoderModel': 'TreeModelEncoderBatchOp',
    'CartRegModel': 'CartRegPredictBatchOp',
    'CrossCandidateSelectorModel': 'CrossCandidateSelectorPredictBatchOp',
    'CrossFeatureModel': 'CrossFeaturePredictBatchOp',
    'DbscanModel': 'DbscanPredictBatchOp',
    'DecisionTreeClassificationModel': 'DecisionTreePredictBatchOp',
    'DecisionTreeEncoderModel': 'TreeModelEncoderBatchOp',
    'DecisionTreeRegEncoderModel': 'TreeModelEncoderBatchOp',
    'DecisionTreeRegressionModel': 'DecisionTreeRegPredictBatchOp',
    'DocCountVectorizerModel': 'DocCountVectorizerPredictBatchOp',
    'DocHashCountVectorizerModel': 'DocHashCountVectorizerPredictBatchOp',
    'EqualWidthDiscretizerModel': 'EqualWidthDiscretizerPredictBatchOp',
    'ExclusiveFeatureBundleModel': 'ExclusiveFeatureBundlePredictBatchOp',
    'FmClassificationModel': 'FmClassifierPredictBatchOp',
    'FmRegressionModel': 'FmRegressorPredictBatchOp',
    'GbdtClassificationModel': 'GbdtPredictBatchOp',
    'GbdtEncoderModel': 'GbdtEncoderPredictBatchOp',
    'GbdtRegEncoderModel': 'TreeModelEncoderBatchOp',
    'GbdtRegressionModel': 'GbdtRegPredictBatchOp',
    'GeneralizedLinearRegressionModel': 'GlmPredictBatchOp',
    'GeoKMeansModel': 'GeoKMeansPredictBatchOp',
    'GroupScoreModel': 'GroupScorecardPredictBatchOp',
    'IForestModelOutlierModel': 'IForestModelOutlierPredictBatchOp',
    'Id3EncoderModel': 'TreeModelEncoderBatchOp',
    'Id3Model': 'Id3PredictBatchOp',
    'KModesModel': 'KModesPredictBatchOp',
    'KerasSequentialClassificationModel': 'KerasSequentialClassifierPredictBatchOp',
    'KerasSequentialRegressionModel': 'KerasSequentialRegressorPredictBatchOp',
    'KnnClassificationModel': 'KnnPredictBatchOp',
    'LassoRegressionModel': 'LassoRegPredictBatchOp',
    'LinearRegStepwiseModel': 'LinearRegStepwisePredictBatchOp',
    'LinearRegressionModel': 'LinearRegPredictBatchOp',
    'LinearSvmModel': 'LinearSvmPredictBatchOp',
    'LinearSvrModel': 'LinearSvrPredictBatchOp',
    'LogisticRegressionModel': 'LogisticRegressionPredictBatchOp',
    'Lookup': 'LookupBatchOp',
    'MaxAbsScalerModel': 'MaxAbsScalerPredictBatchOp',
    'MultiHotEncoderModel': 'MultiHotPredictBatchOp',
    'MultiStringIndexerModel': 'MultiStringIndexerPredictBatchOp',
    'MultilayerPerceptronClassificationModel': 'MultilayerPerceptronPredictBatchOp',
    'NaiveBayesTextModel': 'NaiveBayesTextPredictBatchOp',
    'OcsvmModelOutlierModel': 'OcsvmModelOutlierPredictBatchOp',
    'OneVsRestModel': 'OneVsRestPredictBatchOp',
    'RandomForestClassificationModel': 'RandomForestPredictBatchOp',
    'RandomForestEncoderModel': 'TreeModelEncoderBatchOp',
    'RandomForestRegEncoderModel': 'TreeModelEncoderBatchOp',
    'RandomForestRegressionModel': 'RandomForestRegPredictBatchOp',
    'RecommendationRanking': 'RecommendationRankingBatchOp',
    'RidgeRegressionModel': 'RidgeRegPredictBatchOp',
    'ScoreModel': 'ScorecardPredictBatchOp',
    'ScorecardModel': 'ScorecardPredictBatchOp',
    'SimpleGroupScoreModel': 'GroupScorecardPredictBatchOp',
    'SoftmaxModel': 'SoftmaxPredictBatchOp',
    'StringApproxNearestNeighborModel': 'StringApproxNearestNeighborPredictBatchOp',
    'StringNearestNeighborModel': 'StringNearestNeighborPredictBatchOp',
    'TFTableModelClassificationModel': 'TFTableModelClassifierPredictBatchOp',
    'TFTableModelRegressionModel': 'TFTableModelRegressorPredictBatchOp',
    'TargetEncoderModel': 'TargetEncoderPredictBatchOp',
    'TextApproxNearestNeighborModel': 'TextApproxNearestNeighborPredictBatchOp',
    'TextNearestNeighborModel': 'TextNearestNeighborPredictBatchOp',
    'VectorApproxNearestNeighborModel': 'VectorApproxNearestNeighborPredictBatchOp',
    'VectorImputerModel': 'VectorImputerPredictBatchOp',
    'VectorMaxAbsScalerModel': 'VectorMaxAbsScalerPredictBatchOp',
    'VectorMinMaxScalerModel': 'VectorMinMaxScalerPredictBatchOp',
    'VectorNearestNeighborModel': 'VectorNearestNeighborPredictBatchOp',
    'VectorStandardScalerModel': 'VectorStandardScalerPredictBatchOp',
    'XGBoostClassificationModel': 'XGBoostPredictBatchOp',
    'XGBoostRegressionModel': 'XGBoostRegPredictBatchOp',
}

TRANSFORMERS: Dict[str, str] = {
    'Binarizer': 'BinarizerBatchOp',
    'Bucketizer': 'BucketizerBatchOp',
    'ColumnsToCsv': 'ColumnsToCsvBatchOp',
    'ColumnsToJson': 'ColumnsToJsonBatchOp',
    'ColumnsToKv': 'ColumnsToKvBatchOp',
    'ColumnsToVector': 'ColumnsToVectorBatchOp',
    'CsvToColumns': 'CsvToColumnsBatchOp',
    'CsvToJson': 'CsvToJsonBatchOp',
    'CsvToKv': 'CsvToKvBatchOp',
    'CsvToVector': 'CsvToVectorBatchOp',
    'DCT': 'DCTBatchOp',
    'Dbscan': 'DbscanBatchOp',
    'ExtractMfccFeature': 'ExtractMfccFeatureBatchOp',
    'HashCrossFeature': 'HashCrossFeatureBatchOp',
    'IForestOutlier4GroupedData': 'IForestOutlier4GroupedDataBatchOp',
    'JsonToColumns': 'JsonToColumnsBatchOp',
    'JsonToCsv': 'JsonToCsvBatchOp',
    'JsonToKv': 'JsonToKvBatchOp',
    'JsonToVector': 'JsonToVectorBatchOp',
    'JsonValue': 'JsonValueBatchOp',
    'KvToColumns': 'KvToColumnsBatchOp',
    'KvToCsv': 'KvToCsvBatchOp',
    'KvToJson': 'KvToJsonBatchOp',
    'KvToVector': 'KvToVectorBatchOp',
    'LookupHBase': 'LookupHBaseBatchOp',
    'LookupRedisRow': 'LookupRedisRowBatchOp',
    'LookupRedisString': 'LookupRedisStringBatchOp',
    'NGram': 'NGramBatchOp',
    'OnnxModelPredictor': 'OnnxModelPredictBatchOp',
    'ReadAudioToTensor': 'ReadAudioToTensorBatchOp',
    'ReadImageToTensor': 'ReadImageToTensorBatchOp',
    'RegexTokenizer': 'RegexTokenizerBatchOp',
    'Segment': 'SegmentBatchOp',
    'StopWordsRemover': 'StopWordsRemoverBatchOp',
    'StringSimilarityPairwise': 'StringSimilarityPairwiseBatchOp',
    'TFSavedModelPredictor': 'TFSavedModelPredictBatchOp',
    'TensorReshape': 'TensorReshapeBatchOp',
    'TensorToVector': 'TensorToVectorBatchOp',
    'TextSimilarityPairwise': 'TextSimilarityPairwiseBatchOp',
    'ToMTable': 'ToMTableBatchOp',
    'ToTensor': 'ToTensorBatchOp',
    'ToVector': 'ToVectorBatchOp',
    'Tokenizer': 'TokenizerBatchOp',
    'TorchModelPredictor': 'TorchModelPredictBatchOp',
    'VectorBiFunction': 'VectorBiFunctionBatchOp',
    'VectorElementwiseProduct': 'VectorElementwiseProductBatchOp',
    'VectorFunction': 'VectorFunctionBatchOp',
    'VectorInteraction': 'VectorInteractionBatchOp',
    'VectorNormalizer': 'VectorNormalizeBatchOp',
    'VectorPolynomialExpand': 'VectorPolynomialExpandBatchOp',
    'VectorSizeHint': 'VectorSizeHintBatchOp',
    'VectorSlicer': 'VectorSliceBatchOp',
    'VectorToColumns': 'VectorToColumnsBatchOp',
    'VectorToCsv': 'VectorToCsvBatchOp',
    'VectorToJson': 'VectorToJsonBatchOp',
    'VectorToKv': 'VectorToKvBatchOp',
    'VectorToTensor': 'VectorToTensorBatchOp',
    'WriteTensorToImage': 'WriteTensorToImageBatchOp',
}

RECOMMENDERS: Dict[str, str] = {
    'AlsItemsPerUserRecommender': 'AlsItemsPerUserRecommBatchOp',
    'AlsRateRecommender': 'AlsRateRecommBatchOp',
    'AlsSimilarItemsRecommender': 'AlsSimilarItemsRecommBatchOp',
    'AlsSimilarUsersRecommender': 'AlsSimilarUsersRecommBatchOp',
    'AlsUsersPerItemRecommender': 'AlsUsersPerItemRecommBatchOp',
    'FmItemsPerUserRecommender': 'FmItemsPerUserRecommBatchOp',
    'FmRateRecommender': 'FmRateRecommBatchOp',
    'FmUsersPerItemRecommender': 'FmUsersPerItemRecommBatchOp',
    'ItemCfItemsPerUserRecommender': 'ItemCfItemsPerUserRecommBatchOp',
    'ItemCfRateRecommender': 'ItemCfRateRecommBatchOp',
    'ItemCfSimilarItemsRecommender': 'ItemCfSimilarItemsRecommBatchOp',
    'ItemCfUsersPerItemRecommender': 'ItemCfUsersPerItemRecommBatchOp',
    'SwingSimilarItemsRecommender': 'SwingSimilarItemsRecommBatchOp',
    'UserCfItemsPerUserRecommender': 'UserCfItemsPerUserRecommBatchOp',
    'UserCfRateRecommender': 'UserCfRateRecommBatchOp',
    'UserCfSimilarUsersRecommender': 'UserCfSimilarUsersRecommBatchOp',
    'UserCfUsersPerItemRecommender': 'UserCfUsersPerItemRecommBatchOp',
    'VecDotItemsPerUserRecommender': 'VecDotItemsPerUserRecommBatchOp',
}


# serving-only param names: when train and predict ops both define one, the
# predict op's definition (default/validator) is the one the estimator's
# transform path actually honors, so mirror that — not first-wins
_SERVING_PARAM_NAMES = frozenset(
    {"predictionCol", "predictionDetailCol", "reservedCols"})


def _mirror_params(*op_classes) -> Dict[str, ParamInfo]:
    out: Dict[str, ParamInfo] = {}
    for cls in op_classes:
        mine: Dict[str, ParamInfo] = {}
        for klass in cls.__mro__:
            for k, v in vars(klass).items():
                if isinstance(v, ParamInfo) and k not in mine:
                    mine[k] = v  # most-derived definition wins within a class
        for k, v in mine.items():
            if k not in out or (
                out[k] is not v and v.name in _SERVING_PARAM_NAMES
            ):
                out[k] = v
    return out


def _doc(ref_kind: str, name: str) -> str:
    return (f"Generated pipeline stage (reference: pipeline/**/{name}.java"
            f" — {ref_kind}).")


class BaseRecommender(ModelBase):
    """Base of the generated recommenders (reference:
    pipeline/recommendation/BaseRecommender.java): holds the trained
    recommendation model, transform links the bound recomm op."""


def _make_model(name: str, predict_op: Type, base=ModelBase) -> type:
    cls = type(name, (base,), {
        "__doc__": _doc("ModelBase subclass", name),
        "__module__": __name__,
        "_predict_op_cls": predict_op,
        **_mirror_params(predict_op),
    })
    return cls


def _build():
    g = globals()
    # hand-written stages (estimators.py + bases) take precedence: never
    # generate a class whose name they already define, or the generated
    # twin would shadow them in the package namespace and STAGE_REGISTRY
    from . import estimators as _hand
    from .base import STAGE_REGISTRY as _reg

    existing = {n for n in vars(_hand) if not n.startswith("_")}
    existing |= set(_reg)

    def taken(name):
        return name in g or name in existing

    def put(cls):
        g[cls.__name__] = cls
        __all__.append(cls.__name__)

    for name, predict_name in MODELS.items():
        if taken(name):
            continue
        put(_make_model(name, getattr(_B, predict_name)))

    for name, (train_name, predict_name, model_name) in ESTIMATORS.items():
        train_op = getattr(_B, train_name)
        predict_op = getattr(_B, predict_name)
        if not taken(model_name):
            put(_make_model(model_name, predict_op))
        model_cls = g.get(model_name) or _reg.get(model_name) \
            or getattr(_hand, model_name, None)
        if taken(name):
            continue
        put(type(name, (EstimatorBase,), {
            "__doc__": _doc(f"Trainer over {train_name}", name),
            "__module__": __name__,
            "_train_op_cls": train_op,
            "_model_cls": model_cls,
            **_mirror_params(train_op, predict_op),
        }))

    for name, op_name in TRANSFORMERS.items():
        if taken(name):
            continue
        op = getattr(_B, op_name)
        put(type(name, (TransformerBase,), {
            "__doc__": _doc(f"MapTransformer over {op_name}", name),
            "__module__": __name__,
            "_map_op_cls": op,
            **_mirror_params(op),
        }))

    for name, op_name in RECOMMENDERS.items():
        if taken(name):
            continue
        put(_make_model(name, getattr(_B, op_name), base=BaseRecommender))


_build()
__all__.append("BaseRecommender")

"""Timeseries family: ARIMA, HoltWinters, GARCH, shift/difference, eval.

Capability parity with the reference timeseries package (reference:
core/src/main/java/com/alibaba/alink/operator/batch/timeseries/
ArimaBatchOp.java + common/timeseries/arima/ (CSS fitting in
ArimaEstimate.java), HoltWintersBatchOp.java + common/timeseries/holtwinters/,
GarchBatchOp.java + common/timeseries/garch/, ShiftBatchOp.java,
DifferenceBatchOp.java, operator/batch/evaluation/EvalTimeSeriesBatchOp.java).

TPU-first re-design:
- Every recursion (ARMA residuals, GARCH variance, Holt-Winters smoothing)
  is a ``lax.scan`` — one compiled kernel per series length, reused across
  groups of equal length.
- ARIMA/GARCH likelihoods are minimized with optax.adam on the scan'd loss
  (the reference hand-rolls per-model gradient loops in Java).
- Holt-Winters parameter search evaluates the WHOLE (alpha, beta, gamma) grid
  in one ``vmap`` over the scan — a few thousand candidate smoothings run as
  one batched device program.
- Grouped series run host-side over groups (ragged lengths), sharing the
  compiled kernels via shape-keyed jit caching.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ...common.exceptions import (AkIllegalArgumentException,
                                  AkIllegalDataException)
from ...common.linalg import DenseVector
from ...common.mtable import AlinkTypes, MTable, TableSchema
from ...common.params import InValidator, MinValidator, ParamInfo
from ...mapper import HasSelectedCol
from .base import BatchOperator


class _BaseForecastOp(BatchOperator):
    """Shared frame: group rows by groupCol (ordered by appearance), forecast
    ``predictNum`` steps per series, emit (group?, forecast vector)."""

    VALUE_COL = ParamInfo("valueCol", str, optional=False,
                          aliases=("selectedCol",))
    GROUP_COL = ParamInfo("groupCol", str)
    PREDICT_NUM = ParamInfo("predictNum", int, default=12,
                            validator=MinValidator(1))
    PREDICTION_COL = ParamInfo("predictionCol", str, default="forecast")

    _min_inputs = 1
    _max_inputs = 1

    def _forecast(self, y: np.ndarray, horizon: int) -> np.ndarray:
        raise NotImplementedError

    def _extra_outputs(self, y: np.ndarray) -> Dict[str, float]:
        return {}

    def _execute_impl(self, t: MTable) -> MTable:
        value_col = self.get(self.VALUE_COL)
        group_col = self.get(self.GROUP_COL)
        horizon = int(self.get(self.PREDICT_NUM))
        pred_col = self.get(self.PREDICTION_COL)
        vals = np.asarray(t.col(value_col), np.float64)
        if group_col:
            groups = np.asarray(t.col(group_col), object)
            order: List = []
            idx_of: Dict = {}
            for g in groups:
                if g not in idx_of:
                    idx_of[g] = len(order)
                    order.append(g)
            out_groups, out_vecs, extras = [], [], []
            for g in order:
                y = vals[groups == g]
                out_groups.append(g)
                out_vecs.append(DenseVector(self._forecast(y, horizon)))
                extras.append(self._extra_outputs(y))
        else:
            out_groups = None
            # forecast BEFORE extras — same order as the grouped branch
            # (extras may reuse state from the fit, e.g. DeepAR's sigma)
            out_vecs = [DenseVector(self._forecast(vals, horizon))]
            extras = [self._extra_outputs(vals)]
        cols: Dict = {}
        names, types = [], []
        if out_groups is not None:
            cols[group_col] = np.asarray(out_groups, object)
            names.append(group_col)
            types.append(AlinkTypes.STRING)
        cols[pred_col] = np.asarray(out_vecs, object)
        names.append(pred_col)
        types.append(AlinkTypes.DENSE_VECTOR)
        for key in (extras[0] or {}):
            cols[key] = np.asarray([e[key] for e in extras], np.float64)
            names.append(key)
            types.append(AlinkTypes.DOUBLE)
        return MTable(cols, TableSchema(names, types))

    def _out_schema(self, in_schema: TableSchema) -> TableSchema:
        group_col = self.get(self.GROUP_COL)
        pred_col = self.get(self.PREDICTION_COL)
        names, types = [], []
        if group_col:
            names.append(group_col)
            types.append(AlinkTypes.STRING)
        names.append(pred_col)
        types.append(AlinkTypes.DENSE_VECTOR)
        for key in self._extra_schema_keys():
            names.append(key)
            types.append(AlinkTypes.DOUBLE)
        return TableSchema(names, types)

    def _extra_schema_keys(self) -> List[str]:
        return []


# ---------------------------------------------------------------------------
# ARIMA
# ---------------------------------------------------------------------------

import functools as _functools


@_functools.lru_cache(maxsize=64)
def _arma_fit_fn(p: int, q: int, steps: int, lr: float):
    """Compiled CSS fitter for a given (p, q) — cached so AutoArima's order
    search compiles each candidate order ONCE (and jax re-traces only when
    the series length changes)."""
    import jax
    import jax.numpy as jnp
    import optax

    m = max(p, q)
    opt = optax.adam(lr)

    def css(params, wj):
        n = wj.shape[0]
        c = params[0]
        phi = params[1:1 + p]
        theta = params[1 + p:1 + p + q]

        def step(carry, t):
            w_hist, e_hist = carry          # (p,), (q,)
            pred = c
            if p:
                pred = pred + (phi * w_hist).sum()
            if q:
                pred = pred + (theta * e_hist).sum()
            e_t = wj[t] - pred
            if p:
                w_hist = jnp.concatenate([wj[t][None], w_hist[:-1]])
            if q:
                e_hist = jnp.concatenate([e_t[None], e_hist[:-1]])
            return (w_hist, e_hist), e_t

        w0 = jnp.zeros((max(p, 1),), jnp.float32)
        e0 = jnp.zeros((max(q, 1),), jnp.float32)
        _, errs = jax.lax.scan(step, (w0, e0), jnp.arange(m, n))
        return (errs * errs).sum() / (n - m)

    @jax.jit
    def fit(params0, wj):
        state0 = opt.init(params0)

        def body(_, carry):
            params, state = carry
            g = jax.grad(css)(params, wj)
            updates, state = opt.update(g, state)
            return optax.apply_updates(params, updates), state

        params, _ = jax.lax.fori_loop(0, steps, body, (params0, state0))
        return params, css(params, wj)

    return fit


def _arma_css_fit(w: np.ndarray, p: int, q: int, steps: int = 400,
                  lr: float = 0.05):
    """Conditional-sum-of-squares ARMA(p,q) fit on the (differenced) series.
    Returns (c, phi, theta, sigma2). The residual recursion is a lax.scan;
    adam minimizes the scan'd CSS (reference: arima/ArimaEstimate.java CSS
    method)."""
    import jax
    import jax.numpy as jnp

    fit = _arma_fit_fn(p, q, steps, lr)
    params0 = jnp.zeros(1 + p + q, jnp.float32)
    params0 = params0.at[0].set(float(w.mean()))
    params, sigma2 = jax.device_get(
        fit(params0, jnp.asarray(w, jnp.float32)))
    c = float(params[0])
    phi = np.asarray(params[1:1 + p], np.float64)
    theta = np.asarray(params[1 + p:1 + p + q], np.float64)
    return c, phi, theta, float(sigma2)


def _arima_forecast(y: np.ndarray, p: int, d: int, q: int,
                    horizon: int) -> np.ndarray:
    """Fit ARIMA(p,d,q) by CSS and forecast ``horizon`` steps (shared by
    ArimaBatchOp and AutoArimaBatchOp)."""
    w = np.diff(y, n=d) if d else y.astype(np.float64)
    c, phi, theta, _ = _arma_css_fit(w, p, q)
    # re-run the residual recursion host-side, then iterate forward
    m = max(p, q)
    e_hist = [0.0] * max(q, 1)
    # zero-seed the history exactly as the CSS scan in _arma_css_fit does,
    # so forecast residuals match what the optimizer minimized
    w_hist = [0.0] * max(p, 1)
    for t in range(m, len(w)):
        pred = c + sum(ph * wh for ph, wh in zip(phi, w_hist)) \
            + sum(th * eh for th, eh in zip(theta, e_hist))
        e = w[t] - pred
        w_hist = [w[t]] + w_hist[:-1]
        e_hist = [e] + e_hist[:-1]
    fc_w = []
    for _ in range(horizon):
        pred = c + sum(ph * wh for ph, wh in zip(phi, w_hist)) \
            + sum(th * eh for th, eh in zip(theta, e_hist))
        fc_w.append(pred)
        w_hist = [pred] + w_hist[:-1]
        e_hist = [0.0] + e_hist[:-1]
    # invert differencing: integrate back up through each diff level
    levels = [np.asarray(y, np.float64)]
    for _ in range(d):
        levels.append(np.diff(levels[-1]))
    fc = np.asarray(fc_w, np.float64)
    for k in range(d, 0, -1):
        fc = np.cumsum(fc) + levels[k - 1][-1]
    return fc


class ArimaBatchOp(_BaseForecastOp):
    """(reference: ArimaBatchOp.java — order (p,d,q), CSS estimation)"""

    ORDER = ParamInfo("order", list, default=[1, 1, 1])

    def _fit_params(self):
        order = self.get(self.ORDER)
        if len(order) != 3:
            raise AkIllegalArgumentException("ARIMA order must be [p, d, q]")
        return int(order[0]), int(order[1]), int(order[2])

    def _forecast(self, y: np.ndarray, horizon: int) -> np.ndarray:
        p, d, q = self._fit_params()
        return _arima_forecast(y, p, d, q, horizon)


class AutoArimaBatchOp(_BaseForecastOp):
    """Order search over (p, d, q) by AIC on the CSS fit (reference:
    AutoArimaBatchOp.java — its ICQ grid evaluation collapses to a host
    loop over the jitted CSS objective; AIC = n*log(sigma2) + 2*(p+q+1)).
    The chosen order is emitted in p/d/q columns."""

    MAX_P = ParamInfo("maxP", int, default=3, aliases=("maxOrder",))
    MAX_D = ParamInfo("maxD", int, default=2)
    MAX_Q = ParamInfo("maxQ", int, default=3)

    def _pick_order(self, y: np.ndarray):
        best = None
        for d in range(int(self.get(self.MAX_D)) + 1):
            w = np.diff(y, n=d) if d else y.astype(np.float64)
            if len(w) < 8:
                continue
            n = len(w)
            for p_ in range(int(self.get(self.MAX_P)) + 1):
                for q_ in range(int(self.get(self.MAX_Q)) + 1):
                    if p_ == 0 and q_ == 0 and d == 0:
                        continue
                    _, _, _, sigma2 = _arma_css_fit(w, p_, q_)
                    if not np.isfinite(sigma2) or sigma2 <= 0:
                        continue
                    aic = n * np.log(sigma2) + 2 * (p_ + q_ + 1)
                    if best is None or aic < best[0]:
                        best = (aic, p_, d, q_)
        if best is None:
            raise AkIllegalDataException(
                "series too short for AutoArima order search")
        return best[1], best[2], best[3]

    def _forecast(self, y: np.ndarray, horizon: int) -> np.ndarray:
        p, d, q = self._pick_order(y)
        self._chosen = (p, d, q)
        return _arima_forecast(y, p, d, q, horizon)

    def _extra_outputs(self, y: np.ndarray) -> Dict[str, float]:
        p, d, q = self._chosen
        return {"p": float(p), "d": float(d), "q": float(q)}

    def _extra_schema_keys(self) -> List[str]:
        return ["p", "d", "q"]


class HoltWintersBatchOp(_BaseForecastOp):
    """Triple exponential smoothing, additive trend/seasonality (reference:
    HoltWintersBatchOp.java + holtwinters/HoltWintersUtil.java). When alpha/
    beta/gamma are unset, the whole parameter grid is evaluated in one vmap
    and the SSE-minimizing triple wins."""

    FREQUENCY = ParamInfo("frequency", int, default=4, validator=MinValidator(1))
    ALPHA = ParamInfo("alpha", float)
    BETA = ParamInfo("beta", float)
    GAMMA = ParamInfo("gamma", float)
    DO_TREND = ParamInfo("doTrend", bool, default=True)
    DO_SEASONAL = ParamInfo("doSeasonal", bool, default=True)

    def _forecast(self, y: np.ndarray, horizon: int) -> np.ndarray:
        import jax
        import jax.numpy as jnp

        freq = int(self.get(self.FREQUENCY))
        do_trend = self.get(self.DO_TREND)
        do_seasonal = self.get(self.DO_SEASONAL) and len(y) >= 2 * freq
        yj = jnp.asarray(y, jnp.float32)
        n = len(y)

        if do_seasonal:
            season0 = y[:freq] - y[:freq].mean()
        else:
            season0 = np.zeros(max(freq, 1))
        level0 = float(y[:freq].mean()) if do_seasonal else float(y[0])
        trend0 = float((y[freq:2 * freq].mean() - y[:freq].mean()) / freq) \
            if do_seasonal and len(y) >= 2 * freq else 0.0

        def smooth(abg):
            alpha, beta, gamma = abg

            def step(carry, t):
                level, trend, season = carry
                s_t = season[0]
                yhat = level + trend + (s_t if do_seasonal else 0.0)
                err = yj[t] - yhat
                new_level = alpha * (yj[t] - (s_t if do_seasonal else 0.0)) \
                    + (1 - alpha) * (level + trend)
                new_trend = (beta * (new_level - level) + (1 - beta) * trend) \
                    if do_trend else 0.0
                if do_seasonal:
                    new_s = gamma * (yj[t] - new_level) + (1 - gamma) * s_t
                    season = jnp.concatenate([season[1:], new_s[None]])
                return (new_level, new_trend, season), err

            carry0 = (jnp.asarray(level0, jnp.float32),
                      jnp.asarray(trend0, jnp.float32),
                      jnp.asarray(season0, jnp.float32))
            (level, trend, season), errs = jax.lax.scan(
                step, carry0, jnp.arange(0, n))
            return (errs * errs).sum(), level, trend, season

        alpha = self.get(self.ALPHA)
        if alpha is not None:
            a = float(alpha)
            beta_p, gamma_p = self.get(self.BETA), self.get(self.GAMMA)
            b = 0.1 if beta_p is None else float(beta_p)
            g = 0.1 if gamma_p is None else float(gamma_p)
            _, level, trend, season = jax.jit(smooth)(
                jnp.asarray([a, b, g], jnp.float32))
        else:
            grid = np.linspace(0.05, 0.95, 10, dtype=np.float32)
            cand = np.stack(np.meshgrid(grid, grid, grid),
                            axis=-1).reshape(-1, 3)
            sses, levels, trends, seasons = jax.jit(
                jax.vmap(smooth))(jnp.asarray(cand))
            best = int(np.argmin(np.asarray(sses)))
            level, trend, season = (np.asarray(levels)[best],
                                    np.asarray(trends)[best],
                                    np.asarray(seasons)[best])
        level, trend = float(level), float(trend)
        season = np.asarray(season, np.float64)
        fc = []
        for h in range(1, horizon + 1):
            s = season[(h - 1) % freq] if do_seasonal else 0.0
            fc.append(level + h * trend + s)
        return np.asarray(fc, np.float64)


class GarchBatchOp(_BaseForecastOp):
    """GARCH(1,1) conditional-variance model; forecasts volatility
    (reference: GarchBatchOp.java + garch/GarchEstimate.java)."""

    def _extra_schema_keys(self):
        return ["omega", "alpha", "beta", "unconditionalVariance"]

    def _fit(self, y: np.ndarray):
        import jax
        import jax.numpy as jnp
        import optax

        # memoize: _extra_outputs and _forecast both need the same fit
        key = (y.tobytes(), y.shape[0])
        cached = getattr(self, "_fit_cache", None)
        if cached is not None and cached[0] == key:
            return cached[1]

        r = y - y.mean()
        rj = jnp.asarray(r, jnp.float32)
        var0 = float(r.var()) + 1e-8

        def nll(params):
            # positivity via softplus; alpha+beta<1 not hard-enforced (CSS)
            omega = jax.nn.softplus(params[0]) * var0 * 0.1
            alpha = jax.nn.sigmoid(params[1]) * 0.5
            beta = jax.nn.sigmoid(params[2])

            def step(h, t):
                h_new = omega + alpha * rj[t - 1] ** 2 + beta * h
                return h_new, 0.5 * (jnp.log(h_new) + rj[t] ** 2 / h_new)

            _, losses = jax.lax.scan(step, jnp.asarray(var0, jnp.float32),
                                     jnp.arange(1, len(r)))
            return losses.sum()

        opt = optax.adam(0.05)

        @jax.jit
        def fit(p0):
            s0 = opt.init(p0)

            def body(_, carry):
                p, s = carry
                g = jax.grad(nll)(p)
                upd, s = opt.update(g, s)
                return optax.apply_updates(p, upd), s

            return jax.lax.fori_loop(0, 400, body, (p0, s0))[0]

        p = np.asarray(jax.device_get(fit(jnp.zeros(3, jnp.float32))))
        omega = float(np.log1p(np.exp(p[0])) * var0 * 0.1)
        alpha = float(1 / (1 + np.exp(-p[1])) * 0.5)
        beta = float(1 / (1 + np.exp(-p[2])))
        result = (r, omega, alpha, beta, var0)
        self._fit_cache = (key, result)
        return result

    def _extra_outputs(self, y: np.ndarray):
        r, omega, alpha, beta, var0 = self._fit(y)
        denom = max(1.0 - alpha - beta, 1e-6)
        return {"omega": omega, "alpha": alpha, "beta": beta,
                "unconditionalVariance": omega / denom}

    def _forecast(self, y: np.ndarray, horizon: int) -> np.ndarray:
        r, omega, alpha, beta, var0 = self._fit(y)
        h = var0
        for t in range(1, len(r)):
            h = omega + alpha * r[t - 1] ** 2 + beta * h
        fc = []
        h_next = omega + alpha * r[-1] ** 2 + beta * h
        for _ in range(horizon):
            fc.append(h_next)
            h_next = omega + (alpha + beta) * h_next
        return np.sqrt(np.asarray(fc, np.float64))  # volatility forecast


# ---------------------------------------------------------------------------
# Shift / difference
# ---------------------------------------------------------------------------

class ShiftBatchOp(BatchOperator, HasSelectedCol):
    """Appends the series shifted by shiftNum (reference: ShiftBatchOp.java)."""

    SHIFT_NUM = ParamInfo("shiftNum", int, default=1)
    OUTPUT_COL = ParamInfo("outputCol", str, default="shifted")

    _min_inputs = 1
    _max_inputs = 1

    def _execute_impl(self, t: MTable) -> MTable:
        col = self.get(HasSelectedCol.SELECTED_COL)
        k = int(self.get(self.SHIFT_NUM))
        out = self.get(self.OUTPUT_COL)
        arr = np.asarray(t.col(col), np.float64)
        shifted = np.full_like(arr, np.nan)
        if k >= 0:
            k = min(k, len(arr))
            shifted[k:] = arr[:len(arr) - k]
        else:
            k = max(k, -len(arr))
            shifted[:len(arr) + k] = arr[-k:]
        return t.with_column(out, shifted, AlinkTypes.DOUBLE)

    def _out_schema(self, in_schema):
        out = self.get(self.OUTPUT_COL)
        return TableSchema(list(in_schema.names) + [out],
                           list(in_schema.types) + [AlinkTypes.DOUBLE])


class DifferenceBatchOp(BatchOperator, HasSelectedCol):
    """Appends the differenced series (reference: DifferenceBatchOp.java)."""

    DIFFERENCE_ORDER = ParamInfo("differenceOrder", int, default=1,
                                 validator=MinValidator(1))
    OUTPUT_COL = ParamInfo("outputCol", str, default="diff")

    _min_inputs = 1
    _max_inputs = 1

    def _execute_impl(self, t: MTable) -> MTable:
        col = self.get(HasSelectedCol.SELECTED_COL)
        d = int(self.get(self.DIFFERENCE_ORDER))
        out = self.get(self.OUTPUT_COL)
        arr = np.asarray(t.col(col), np.float64)
        diffed = arr.copy()
        for _ in range(d):
            diffed = np.concatenate([[np.nan], np.diff(diffed)])
        return t.with_column(out, diffed, AlinkTypes.DOUBLE)

    def _out_schema(self, in_schema):
        out = self.get(self.OUTPUT_COL)
        return TableSchema(list(in_schema.names) + [out],
                           list(in_schema.types) + [AlinkTypes.DOUBLE])


# ---------------------------------------------------------------------------
# Timeseries evaluation
# ---------------------------------------------------------------------------

_TS_METRIC_SCHEMA = TableSchema(
    ["mse", "rmse", "mae", "mape", "smape", "r2"],
    [AlinkTypes.DOUBLE] * 6)


class EvalTimeSeriesBatchOp(BatchOperator):
    """Forecast-accuracy metrics (reference: EvalTimeSeriesBatchOp.java +
    common/evaluation/TimeSeriesMetrics.java)."""

    LABEL_COL = ParamInfo("labelCol", str, optional=False)
    PREDICTION_COL = ParamInfo("predictionCol", str, optional=False)

    _min_inputs = 1
    _max_inputs = 1

    def _execute_impl(self, t: MTable) -> MTable:
        y = np.asarray(t.col(self.get(self.LABEL_COL)), np.float64)
        p = np.asarray(t.col(self.get(self.PREDICTION_COL)), np.float64)
        ok = ~(np.isnan(y) | np.isnan(p))
        y, p = y[ok], p[ok]
        err = p - y
        mse = float((err ** 2).mean())
        mae = float(np.abs(err).mean())
        denom = np.where(np.abs(y) < 1e-12, 1e-12, np.abs(y))
        mape = float((np.abs(err) / denom).mean())
        sdenom = (np.abs(y) + np.abs(p)) / 2.0
        sdenom = np.where(sdenom < 1e-12, 1e-12, sdenom)
        smape = float((np.abs(err) / sdenom).mean())
        ss_tot = float(((y - y.mean()) ** 2).sum())
        r2 = 1.0 - float((err ** 2).sum()) / max(ss_tot, 1e-12)
        self._metrics = {"mse": mse, "rmse": float(np.sqrt(mse)), "mae": mae,
                         "mape": mape, "smape": smape, "r2": r2}
        return MTable({k: [v] for k, v in self._metrics.items()},
                      _TS_METRIC_SCHEMA)

    def _out_schema(self, in_schema):
        return _TS_METRIC_SCHEMA

    def collect_metrics(self) -> dict:
        self.collect()
        return self._metrics


class DeepARBatchOp(_BaseForecastOp):
    """Probabilistic LSTM forecaster with Gaussian output head (reference:
    akdl deepar model via DLLauncher — operator/batch/timeseries/
    DeepARTrainBatchOp + core/src/main/resources/entries/deepar_entry.py).

    Rides the shared DL train loop: sliding lookback windows train an LSTM
    whose head emits (mu, log_sigma) under Gaussian NLL; forecasting rolls
    the window forward on the predicted mean. ``predictionCol`` holds the
    mean path; sigma of the one-step-ahead distribution lands in the
    ``sigma`` column."""

    LOOKBACK = ParamInfo("lookback", int, default=24, validator=MinValidator(2))
    HIDDEN = ParamInfo("hiddenSize", int, default=32)
    NUM_EPOCHS = ParamInfo("numEpochs", int, default=40)
    BATCH_SIZE = ParamInfo("batchSize", int, default=64)
    LEARNING_RATE = ParamInfo("learningRate", float, default=5e-3)
    RANDOM_SEED = ParamInfo("randomSeed", int, default=0, aliases=("seed",))

    def _extra_schema_keys(self):
        return ["sigma"]

    def _fit_forecast(self, y: np.ndarray, horizon: int):
        from .timeseries2 import deepar_train, net_forecast

        model = deepar_train(
            y, lookback=self.get(self.LOOKBACK),
            hidden=self.get(self.HIDDEN),
            num_epochs=self.get(self.NUM_EPOCHS),
            batch_size=self.get(self.BATCH_SIZE),
            learning_rate=self.get(self.LEARNING_RATE),
            seed=self.get(self.RANDOM_SEED))
        return net_forecast(model, y, horizon)

    def _forecast(self, y: np.ndarray, horizon: int) -> np.ndarray:
        # the base loop calls _forecast then _extra_outputs for each series:
        # stash sigma from this fit so the extra column reuses it
        means, sigma = self._fit_forecast(y, horizon)
        self._last_sigma = sigma
        return means

    def _extra_outputs(self, y: np.ndarray):
        return {"sigma": self._last_sigma}


class LSTNetBatchOp(_BaseForecastOp):
    """LSTNet forecaster: Conv feature extraction + GRU + skip-GRU + an
    autoregressive highway component (reference: akdl lstnet model via
    DLLauncher — core/src/main/python/akdl/akdl/models/tf/lstnet/ +
    resources/entries/lstnet_entry.py).

    Rides the shared DL train loop like DeepAR. The head is trained
    direct-multi-horizon (the LSTNet-paper contract): one forward pass
    emits the whole ``predictNum`` path, instead of compounding one-step
    recursion error across the horizon."""

    LOOKBACK = ParamInfo("lookback", int, default=24,
                         validator=MinValidator(4))
    HIDDEN = ParamInfo("hiddenSize", int, default=32)
    KERNEL_SIZE = ParamInfo("kernelSize", int, default=3)
    SKIP = ParamInfo("skip", int, default=4)
    AR_WINDOW = ParamInfo("arWindow", int, default=8)
    NUM_EPOCHS = ParamInfo("numEpochs", int, default=40)
    BATCH_SIZE = ParamInfo("batchSize", int, default=64)
    LEARNING_RATE = ParamInfo("learningRate", float, default=5e-3)
    RANDOM_SEED = ParamInfo("randomSeed", int, default=0, aliases=("seed",))

    def _forecast(self, y: np.ndarray, horizon: int) -> np.ndarray:
        from .timeseries2 import lstnet_train, net_forecast

        model = lstnet_train(
            y, lookback=self.get(self.LOOKBACK),
            hidden=self.get(self.HIDDEN),
            kernel=self.get(self.KERNEL_SIZE), skip=self.get(self.SKIP),
            ar_window=self.get(self.AR_WINDOW),
            num_epochs=self.get(self.NUM_EPOCHS),
            batch_size=self.get(self.BATCH_SIZE),
            learning_rate=self.get(self.LEARNING_RATE),
            seed=self.get(self.RANDOM_SEED),
            horizon=horizon)     # direct multi-horizon head (LSTNet paper)
        means, _ = net_forecast(model, y, horizon)
        return means


class ProphetBatchOp(_BaseForecastOp):
    """Prophet forecaster, plugin-gated on the ``prophet`` package
    (reference: operator/common/timeseries/ProphetMapper.java — the
    reference spawns a python subprocess running prophet per mapper; here
    prophet runs in-process when installed, and its absence raises the
    same actionable missing-plugin guidance)."""

    FREQ = ParamInfo("freq", str, default="D",
                     desc="pandas offset alias for the synthetic fit index "
                          "(the series is modeled positionally)")

    def _forecast(self, y: np.ndarray, horizon: int) -> np.ndarray:
        try:
            from prophet import Prophet
        except ImportError as e:
            from ...common.exceptions import AkPluginNotExistException

            raise AkPluginNotExistException(
                "ProphetBatchOp needs the 'prophet' package (the reference "
                "runs it as a python subprocess plugin): pip install "
                "prophet. Built-in alternatives: HoltWintersBatchOp, "
                "AutoArimaBatchOp, DeepARBatchOp, LSTNetBatchOp.") from e
        import pandas as pd

        ds = pd.date_range("2000-01-01", periods=len(y),
                           freq=self.get(self.FREQ))
        m = Prophet()
        m.fit(pd.DataFrame({"ds": ds, "y": y}))
        future = m.make_future_dataframe(periods=horizon,
                                         freq=self.get(self.FREQ))
        fc = m.predict(future)["yhat"].to_numpy()
        return np.asarray(fc[-horizon:], np.float64)


class TFTBatchOp(_BaseForecastOp):
    """Attention-based forecaster in the Temporal-Fusion-Transformer family
    (reference: akdl tft model — core/src/main/python/akdl/akdl/models/tf/
    tft/; this is the single-series core of that design: LSTM encoding +
    multi-head self-attention over the lookback + gated residual head,
    without the multi-covariate variable-selection networks the reference
    wires for exogenous inputs)."""

    LOOKBACK = ParamInfo("lookback", int, default=24,
                         validator=MinValidator(4))
    HIDDEN = ParamInfo("hiddenSize", int, default=32)
    NUM_HEADS = ParamInfo("numHeads", int, default=4)
    NUM_EPOCHS = ParamInfo("numEpochs", int, default=60)
    BATCH_SIZE = ParamInfo("batchSize", int, default=64)
    LEARNING_RATE = ParamInfo("learningRate", float, default=5e-3)
    RANDOM_SEED = ParamInfo("randomSeed", int, default=0, aliases=("seed",))

    def _forecast(self, y: np.ndarray, horizon: int) -> np.ndarray:
        import flax.linen as nn
        import jax
        import jax.numpy as jnp

        from ...dl.train import TrainConfig, train_model

        if len(y) < 12:
            raise AkIllegalArgumentException(
                f"TFT needs at least 12 observations, got {len(y)}")
        L = min(self.get(self.LOOKBACK), max(len(y) - 1, 4))
        mu_y, sd_y = float(np.mean(y)), float(np.std(y) + 1e-9)
        z32 = ((np.asarray(y, np.float64) - mu_y) / sd_y).astype(np.float32)
        X = np.stack([z32[s:s + L] for s in range(len(z32) - L)])[..., None]
        t = z32[L:]

        hidden = self.get(self.HIDDEN)
        heads = max(1, min(self.get(self.NUM_HEADS), hidden))
        while hidden % heads:  # flax SelfAttention needs heads | qkv dims
            heads -= 1

        class GRN(nn.Module):
            """Gated residual network — the TFT building block."""

            units: int

            @nn.compact
            def __call__(self, x):
                h = nn.elu(nn.Dense(self.units)(x))
                h = nn.Dense(self.units)(h)
                gate = nn.sigmoid(nn.Dense(self.units)(x))
                skip = (x if x.shape[-1] == self.units
                        else nn.Dense(self.units)(x))
                return nn.LayerNorm()(skip + gate * h)

        class Net(nn.Module):
            @nn.compact
            def __call__(self, x, deterministic=True):  # (b, L, 1)
                h = nn.Dense(hidden)(x)
                h = nn.RNN(nn.OptimizedLSTMCell(hidden))(h)  # (b, L, h)
                attn = nn.SelfAttention(
                    num_heads=heads, qkv_features=hidden,
                    deterministic=True)(h)
                h = nn.LayerNorm()(h + attn)       # post-attention residual
                h = GRN(hidden)(h)[:, -1, :]       # gated head on last step
                return nn.Dense(1)(h)              # (b, 1) — mse squeezes

        cfg = TrainConfig(num_epochs=self.get(self.NUM_EPOCHS),
                          batch_size=self.get(self.BATCH_SIZE),
                          learning_rate=self.get(self.LEARNING_RATE),
                          loss="mse", seed=self.get(self.RANDOM_SEED))
        net = Net()
        params, _ = train_model(net, {"x": X}, t, cfg, regression=True,
                                seq_axis=None)

        @jax.jit
        def predict(params, window):
            return net.apply(params, window[None],
                             deterministic=True)[0, 0]

        window = z32[-L:].copy()
        preds = []
        for _ in range(horizon):
            nxt = float(jax.device_get(predict(
                params, jnp.asarray(window[..., None]))))
            preds.append(nxt)
            window = np.roll(window, -1)
            window[-1] = nxt
        return np.asarray(preds, np.float64) * sd_y + mu_y

"""Timeseries long-tail: DeepAR/LSTNet/Prophet train+predict pairs,
AutoGarch order search, and in-series lookup ops.

Capability parity (reference: operator/batch/timeseries/
DeepARTrainBatchOp.java / DeepARPredictBatchOp.java,
LSTNetTrainBatchOp.java / LSTNetPredictBatchOp.java,
ProphetTrainBatchOp.java / ProphetPredictBatchOp.java,
AutoGarchBatchOp.java, dataproc/LookupValueInTimeSeriesBatchOp.java,
LookupVectorInTimeSeriesBatchOp.java, LookupRecentDaysBatchOp.java; the
stream twins live in operator/stream/timeseries of the reference).

The reference trains these nets through the akdl DLLauncher subprocess and
persists TF checkpoints; here the SAME flax modules the direct forecast ops
use are trained in-process and the parameter pytree is persisted with flax
serialization inside the standard model table, so predict mappers (and
their auto-generated stream twins) serve them anywhere.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ...common.exceptions import (
    AkIllegalArgumentException,
    AkIllegalDataException,
)
from ...common.linalg import DenseVector, parse_vector
from ...common.model import model_to_table, table_to_model
from ...common.mtable import AlinkTypes, MTable
from ...common.params import MinValidator, ParamInfo
from ...mapper import (
    HasOutputCol,
    HasReservedCols,
    HasSelectedCol,
    ModelMapper,
    SISOMapper,
)
from .base import BatchOperator
from .utils import MapBatchOp, ModelMapBatchOp, ModelTrainOpMixin
from .timeseries import _BaseForecastOp


# ---------------------------------------------------------------------------
# shared flax-net cores (used by the direct ops AND the train/predict pairs)
# ---------------------------------------------------------------------------


def _deepar_net(hidden: int):
    import flax.linen as nn

    class Net(nn.Module):
        @nn.compact
        def __call__(self, x, deterministic=True):
            h = nn.RNN(nn.OptimizedLSTMCell(hidden))(x)[:, -1, :]
            return nn.Dense(2)(h)

    return Net()


def _lstnet_net(hidden: int, kernel: int, skip: int, ar_w: int,
                out_dim: int = 1):
    import flax.linen as nn
    import jax.numpy as jnp

    class Net(nn.Module):
        @nn.compact
        def __call__(self, x, deterministic=True):  # (b, L, 1)
            c = nn.relu(nn.Conv(hidden, (kernel,))(x))
            r = nn.RNN(nn.GRUCell(hidden))(c)[:, -1, :]
            sk = c[:, (c.shape[1] - 1) % skip::skip, :]
            sk = nn.RNN(nn.GRUCell(hidden // 2))(sk)[:, -1, :]
            out = nn.Dense(out_dim)(jnp.concatenate([r, sk], -1))
            ar = nn.Dense(out_dim)(x[:, -ar_w:, 0])
            return out + ar

    return Net()


def _train_windows(z: np.ndarray, L: int, horizon: int = 1):
    """(X, targets) windows; ``horizon > 1`` builds direct multi-step
    targets ``z[s+L : s+L+h]`` per window (the LSTNet-paper contract —
    the net maps a window straight to the forecast path instead of
    compounding one-step recursion error)."""
    n_win = len(z) - L - horizon + 1
    X = np.stack([z[s:s + L] for s in range(n_win)])[..., None]
    if horizon == 1:
        return X.astype(np.float32), z[L:].astype(np.float32)
    t = np.stack([z[s + L:s + L + horizon] for s in range(n_win)])
    return X.astype(np.float32), t.astype(np.float32)


def deepar_train(y: np.ndarray, *, lookback: int, hidden: int,
                 num_epochs: int, batch_size: int, learning_rate: float,
                 seed: int) -> Dict:
    """Fit the DeepAR net; returns the serializable model dict."""
    from flax import serialization

    from ...dl.train import TrainConfig, train_model

    if len(y) < 8:
        raise AkIllegalArgumentException(
            f"DeepAR needs at least 8 observations, got {len(y)}")
    L = min(lookback, max(len(y) - 1, 2))
    mu_y, sd_y = float(np.mean(y)), float(np.std(y) + 1e-9)
    z = (np.asarray(y, np.float64) - mu_y) / sd_y
    X, t = _train_windows(z, L)
    net = _deepar_net(hidden)
    cfg = TrainConfig(num_epochs=num_epochs, batch_size=batch_size,
                      learning_rate=learning_rate, loss="gaussian_nll",
                      seed=seed)
    params, _ = train_model(net, {"x": X}, t, cfg, regression=True,
                            seq_axis=None)
    return {"kind": "deepar", "L": L, "hidden": hidden,
            "mu": mu_y, "sd": sd_y,
            "params_bytes": np.frombuffer(
                serialization.to_bytes(params), np.uint8).copy()}


def lstnet_train(y: np.ndarray, *, lookback: int, hidden: int,
                 kernel: int, skip: int, ar_window: int, num_epochs: int,
                 batch_size: int, learning_rate: float, seed: int,
                 horizon: int = 1) -> Dict:
    """Fit LSTNet. ``horizon > 1`` trains the paper's direct multi-horizon
    head (one forward pass emits the whole forecast path) — the recursive
    1-step roll compounds error over the horizon, which is why the rolled
    forecast used to lose to ARIMA on clean seasonal series (see
    tests/test_timeseries.py::test_lstnet_beats_arima_on_seasonal_series).
    ``horizon=1`` keeps the legacy head for pre-existing saved models and
    the train/predict pair, whose horizon is unknown at train time."""
    from flax import serialization

    from ...dl.train import TrainConfig, train_model

    if len(y) < 12:
        raise AkIllegalArgumentException(
            f"LSTNet needs at least 12 observations, got {len(y)}")
    L = min(lookback, max(len(y) - 1, 4))
    mu_y, sd_y = float(np.mean(y)), float(np.std(y) + 1e-9)
    z = (np.asarray(y, np.float64) - mu_y) / sd_y
    h = max(1, min(int(horizon), len(z) - L - 1))
    X, t = _train_windows(z, L, h)
    skip = max(1, min(skip, L - 1))
    ar_w = max(1, min(ar_window, L))
    net = _lstnet_net(hidden, kernel, skip, ar_w, out_dim=h)
    cfg = TrainConfig(num_epochs=num_epochs, batch_size=batch_size,
                      learning_rate=learning_rate, loss="mse", seed=seed)
    params, _ = train_model(net, {"x": X}, t, cfg, regression=True,
                            seq_axis=None)
    return {"kind": "lstnet", "L": L, "hidden": hidden, "kernel": kernel,
            "skip": skip, "arWindow": ar_w, "horizon": h,
            "mu": mu_y, "sd": sd_y,
            "params_bytes": np.frombuffer(
                serialization.to_bytes(params), np.uint8).copy()}


def _restore_net(model: Dict):
    import jax
    import jax.numpy as jnp
    from flax import serialization

    L = int(model["L"])
    if model["kind"] == "deepar":
        net = _deepar_net(int(model["hidden"]))
    else:
        net = _lstnet_net(int(model["hidden"]), int(model["kernel"]),
                          int(model["skip"]), int(model["arWindow"]),
                          out_dim=int(model.get("horizon", 1)))
    template = net.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, L, 1), jnp.float32))
    params = serialization.from_bytes(
        template, bytes(np.asarray(model["params_bytes"], np.uint8)))
    return net, params


def net_forecast(model: Dict, y_hist: np.ndarray, horizon: int
                 ) -> Tuple[np.ndarray, Optional[float]]:
    """Roll the restored net forward ``horizon`` steps from the end of
    ``y_hist``. Returns (mean path, sigma of the first step for deepar)."""
    import jax
    import jax.numpy as jnp

    net, params = _restore_net(model)
    L = int(model["L"])
    mu_y, sd_y = float(model["mu"]), float(model["sd"])
    z = ((np.asarray(y_hist, np.float64) - mu_y) / sd_y).astype(np.float32)
    if len(z) < L:
        z = np.concatenate([np.zeros(L - len(z), np.float32), z])
    window = z[-L:].copy()

    @jax.jit
    def predict(p, w):
        return net.apply(p, w[None], deterministic=True)[0]

    # direct multi-horizon heads emit their whole head per forward pass (no
    # recursion error inside a block); legacy 1-step heads roll step-wise —
    # either way the loop consumes however many steps the head emitted
    means: List[float] = []
    sigma0: Optional[float] = None
    while len(means) < horizon:
        out = np.asarray(jax.device_get(
            predict(params, jnp.asarray(window[..., None]))))
        if model["kind"] == "deepar":
            mu_steps = [float(out[0])]
            if not means:
                sigma0 = float(np.exp(float(out[1]))) * sd_y
        else:
            mu_steps = [float(v) for v in np.asarray(out).reshape(-1)]
        take = mu_steps[:horizon - len(means)]
        means.extend(m * sd_y + mu_y for m in take)
        window = np.concatenate(
            [window, np.asarray(take, np.float32)])[-L:]
    return np.asarray(means, np.float64), sigma0


# ---------------------------------------------------------------------------
# train ops
# ---------------------------------------------------------------------------


class _NetForecastTrainOp(ModelTrainOpMixin, BatchOperator):
    VALUE_COL = ParamInfo("valueCol", str, optional=False,
                          aliases=("selectedCol",))
    LOOKBACK = ParamInfo("lookback", int, default=24,
                         validator=MinValidator(2))
    HIDDEN = ParamInfo("hiddenSize", int, default=32)
    NUM_EPOCHS = ParamInfo("numEpochs", int, default=40)
    BATCH_SIZE = ParamInfo("batchSize", int, default=64)
    LEARNING_RATE = ParamInfo("learningRate", float, default=5e-3)
    RANDOM_SEED = ParamInfo("randomSeed", int, default=0, aliases=("seed",))

    _min_inputs = 1
    _max_inputs = 1
    _model_name = None

    def _static_meta_keys(self, in_schema):
        return {"modelName": self._model_name}

    def _train(self, y: np.ndarray) -> Dict:
        raise NotImplementedError

    def _execute_impl(self, t: MTable) -> MTable:
        y = np.asarray(t.col(self.get(self.VALUE_COL)), np.float64)
        model = self._train(y)
        arrays = {"params_bytes": model.pop("params_bytes")}
        meta = {"modelName": self._model_name, **model}
        return model_to_table(meta, arrays)


class DeepARTrainBatchOp(_NetForecastTrainOp):
    """(reference: operator/batch/timeseries/DeepARTrainBatchOp.java — the
    akdl deepar estimator behind DLLauncher)."""

    _model_name = "DeepARModel"

    def _train(self, y):
        return deepar_train(
            y, lookback=self.get(self.LOOKBACK),
            hidden=self.get(self.HIDDEN),
            num_epochs=self.get(self.NUM_EPOCHS),
            batch_size=self.get(self.BATCH_SIZE),
            learning_rate=self.get(self.LEARNING_RATE),
            seed=self.get(self.RANDOM_SEED))


class LSTNetTrainBatchOp(_NetForecastTrainOp):
    """(reference: operator/batch/timeseries/LSTNetTrainBatchOp.java)."""

    _model_name = "LSTNetModel"

    KERNEL_SIZE = ParamInfo("kernelSize", int, default=3)
    SKIP = ParamInfo("skip", int, default=4)
    AR_WINDOW = ParamInfo("arWindow", int, default=8)

    def _train(self, y):
        return lstnet_train(
            y, lookback=self.get(self.LOOKBACK),
            hidden=self.get(self.HIDDEN),
            kernel=self.get(self.KERNEL_SIZE), skip=self.get(self.SKIP),
            ar_window=self.get(self.AR_WINDOW),
            num_epochs=self.get(self.NUM_EPOCHS),
            batch_size=self.get(self.BATCH_SIZE),
            learning_rate=self.get(self.LEARNING_RATE),
            seed=self.get(self.RANDOM_SEED))


# ---------------------------------------------------------------------------
# predict mappers/ops
# ---------------------------------------------------------------------------


class _NetForecastPredictMapper(ModelMapper, HasSelectedCol, HasOutputCol,
                                HasReservedCols):
    """Each row's history (vector or MTable series cell) → forecast vector
    (reference: DeepARPredictBatchOp.java over the persisted checkpoint)."""

    PREDICT_NUM = ParamInfo("predictNum", int, default=12,
                            validator=MinValidator(1))

    def load_model(self, model: MTable):
        self.meta, arrays = table_to_model(model)
        self.model = dict(self.meta)
        self.model["params_bytes"] = arrays["params_bytes"]
        return self

    def output_schema(self, input_schema):
        out = self.get(HasOutputCol.OUTPUT_COL) or "forecast"
        return self._append_result_schema(
            input_schema, [out], [AlinkTypes.DENSE_VECTOR])

    @staticmethod
    def _history(cell) -> np.ndarray:
        if isinstance(cell, MTable):
            # last numeric column is the value series
            for name, tp in zip(reversed(cell.names),
                                reversed(list(cell.schema.types))):
                if AlinkTypes.is_numeric(tp):
                    return np.asarray(cell.col(name), np.float64)
            raise AkIllegalDataException("series MTable has no numeric col")
        return parse_vector(cell).to_dense().data

    def map_table(self, t: MTable) -> MTable:
        sel = self.get(HasSelectedCol.SELECTED_COL)
        out = self.get(HasOutputCol.OUTPUT_COL) or "forecast"
        horizon = self.get(self.PREDICT_NUM)
        vecs = np.empty(t.num_rows, object)
        for i, cell in enumerate(t.col(sel)):
            if cell is None:
                vecs[i] = None
                continue
            means, _sigma = net_forecast(self.model, self._history(cell),
                                         horizon)
            vecs[i] = DenseVector(means)
        return self._append_result(
            t, {out: vecs}, {out: AlinkTypes.DENSE_VECTOR})


class DeepARPredictBatchOp(ModelMapBatchOp, HasSelectedCol, HasOutputCol,
                           HasReservedCols):
    """(reference: operator/batch/timeseries/DeepARPredictBatchOp.java)"""

    mapper_cls = _NetForecastPredictMapper
    PREDICT_NUM = _NetForecastPredictMapper.PREDICT_NUM


class LSTNetPredictBatchOp(DeepARPredictBatchOp):
    """(reference: operator/batch/timeseries/LSTNetPredictBatchOp.java)"""


# ---------------------------------------------------------------------------
# Prophet train/predict (plugin-gated like the direct op)
# ---------------------------------------------------------------------------


class ProphetTrainBatchOp(ModelTrainOpMixin, BatchOperator):
    """Fit prophet once and persist its JSON model (reference:
    operator/batch/timeseries/ProphetTrainBatchOp.java — the python
    subprocess plugin collapses to an in-process fit)."""

    VALUE_COL = ParamInfo("valueCol", str, optional=False,
                          aliases=("selectedCol",))
    FREQ = ParamInfo("freq", str, default="D")

    _min_inputs = 1
    _max_inputs = 1

    def _static_meta_keys(self, in_schema):
        return {"modelName": "ProphetModel"}

    def _execute_impl(self, t: MTable) -> MTable:
        try:
            from prophet import Prophet
            from prophet.serialize import model_to_json
        except ImportError as e:
            from ...common.exceptions import AkPluginNotExistException

            raise AkPluginNotExistException(
                "ProphetTrainBatchOp needs the 'prophet' package: "
                "pip install prophet. Built-in alternatives: "
                "AutoArimaBatchOp, DeepARTrainBatchOp, "
                "LSTNetTrainBatchOp.") from e
        import pandas as pd

        y = np.asarray(t.col(self.get(self.VALUE_COL)), np.float64)
        freq = self.get(self.FREQ)
        ds = pd.date_range("2000-01-01", periods=len(y), freq=freq)
        m = Prophet()
        m.fit(pd.DataFrame({"ds": ds, "y": y}))
        payload = model_to_json(m).encode()
        meta = {"modelName": "ProphetModel", "freq": freq,
                "numObservations": int(len(y))}
        return model_to_table(
            meta, {"model_json": np.frombuffer(payload, np.uint8).copy()})


class ProphetPredictMapper(ModelMapper, HasSelectedCol, HasOutputCol,
                           HasReservedCols):
    PREDICT_NUM = ParamInfo("predictNum", int, default=12,
                            validator=MinValidator(1))

    def load_model(self, model: MTable):
        self.meta, arrays = table_to_model(model)
        self._json = bytes(np.asarray(arrays["model_json"],
                                      np.uint8)).decode()
        return self

    def output_schema(self, input_schema):
        out = self.get(HasOutputCol.OUTPUT_COL) or "forecast"
        return self._append_result_schema(
            input_schema, [out], [AlinkTypes.DENSE_VECTOR])

    @staticmethod
    def _row_series(cell) -> "np.ndarray | None":
        if cell is None:
            return None
        if isinstance(cell, MTable):
            for name, tp in zip(reversed(cell.names),
                                reversed(list(cell.schema.types))):
                if AlinkTypes.is_numeric(tp):
                    return np.asarray(cell.col(name), np.float64)
            return None
        return parse_vector(cell).to_dense().data

    def map_table(self, t: MTable) -> MTable:
        try:
            from prophet import Prophet
            from prophet.serialize import model_from_json
        except ImportError as e:
            from ...common.exceptions import AkPluginNotExistException

            raise AkPluginNotExistException(
                "ProphetPredictBatchOp needs the 'prophet' package") from e
        import pandas as pd

        horizon = self.get(self.PREDICT_NUM)
        freq = self.meta["freq"]
        out = self.get(HasOutputCol.OUTPUT_COL) or "forecast"
        sel = self.get(HasSelectedCol.SELECTED_COL)
        cells = t.col(sel) if sel else [None] * t.num_rows
        trained_fc = None
        vecs = np.empty(t.num_rows, object)
        for i in range(t.num_rows):
            y = self._row_series(cells[i]) if sel else None
            if y is not None and len(y) >= 2:
                # per-row refit on the row's own series — the reference
                # runs prophet per mapper row
                ds = pd.date_range("2000-01-01", periods=len(y), freq=freq)
                m = Prophet()
                m.fit(pd.DataFrame({"ds": ds, "y": y}))
                future = m.make_future_dataframe(periods=horizon, freq=freq)
                fc = m.predict(future)["yhat"].to_numpy()[-horizon:]
            else:
                # no per-row series: continue the TRAINING series
                if trained_fc is None:
                    m = model_from_json(self._json)
                    future = m.make_future_dataframe(periods=horizon,
                                                     freq=freq)
                    trained_fc = m.predict(
                        future)["yhat"].to_numpy()[-horizon:]
                fc = trained_fc
            vecs[i] = DenseVector(np.asarray(fc, np.float64))
        return self._append_result(
            t, {out: vecs}, {out: AlinkTypes.DENSE_VECTOR})


class ProphetPredictBatchOp(ModelMapBatchOp, HasSelectedCol, HasOutputCol,
                            HasReservedCols):
    """(reference: operator/batch/timeseries/ProphetPredictBatchOp.java)"""

    mapper_cls = ProphetPredictMapper
    PREDICT_NUM = ProphetPredictMapper.PREDICT_NUM


# ---------------------------------------------------------------------------
# AutoGarch: (p, q) order search by AIC
# ---------------------------------------------------------------------------


def _garch_fit_pq(r: np.ndarray, p: int, q: int
                  ) -> Tuple[float, np.ndarray, np.ndarray, float]:
    """CSS fit of GARCH(p, q): h_t = ω + Σ α_i r²_{t-i} + Σ β_j h_{t-j}.
    Returns (nll, alphas, betas, omega). p, q are static (compile per
    order), the lag recursions ride one lax.scan."""
    import jax
    import jax.numpy as jnp
    import optax

    rj = jnp.asarray(r, jnp.float32)
    var0 = float(np.var(r)) + 1e-8
    m = max(p, q, 1)

    def unpack(params):
        omega = jax.nn.softplus(params[0]) * var0 * 0.1
        alphas = jax.nn.sigmoid(params[1:1 + p]) * (0.5 / max(p, 1))
        betas = jax.nn.sigmoid(params[1 + p:1 + p + q]) / max(q, 1)
        return omega, alphas, betas

    def nll(params):
        omega, alphas, betas = unpack(params)

        def step(carry, t):
            h_hist, r2_hist = carry  # (m,), (m,) most-recent-first
            h_new = omega
            for i in range(p):
                h_new = h_new + alphas[i] * r2_hist[i]
            for j in range(q):
                h_new = h_new + betas[j] * h_hist[j]
            loss = 0.5 * (jnp.log(h_new) + rj[t] ** 2 / h_new)
            h_hist = jnp.concatenate([h_new[None], h_hist[:-1]])
            r2_hist = jnp.concatenate([rj[t][None] ** 2, r2_hist[:-1]])
            return (h_hist, r2_hist), loss

        h0 = jnp.full((m,), var0, jnp.float32)
        r20 = jnp.full((m,), var0, jnp.float32)
        _, losses = jax.lax.scan(step, (h0, r20),
                                 jnp.arange(m, len(r)))
        return losses.sum()

    opt = optax.adam(0.05)

    @jax.jit
    def fit(p0):
        s0 = opt.init(p0)

        def body(_, carry):
            pp, ss = carry
            g = jax.grad(nll)(pp)
            upd, ss = opt.update(g, ss)
            return optax.apply_updates(pp, upd), ss

        return jax.lax.fori_loop(0, 300, body, (p0, s0))[0]

    import jax.numpy as jnp2

    params = np.asarray(jax.device_get(
        fit(jnp2.zeros(1 + p + q, jnp2.float32))))
    final_nll = float(nll(jnp2.asarray(params)))
    import jax as _jax

    omega, alphas, betas = (np.asarray(_jax.device_get(x))
                            for x in unpack(jnp2.asarray(params)))
    return final_nll, np.atleast_1d(alphas), np.atleast_1d(betas), float(omega)


class AutoGarchBatchOp(_BaseForecastOp):
    """GARCH with (p, q) order search by AIC over a small grid — the
    reference's headline auto-order op (reference: operator/batch/
    timeseries/AutoGarchBatchOp.java)."""

    MAX_ORDER = ParamInfo("maxOrder", int, default=2,
                          validator=MinValidator(1))

    def _extra_schema_keys(self):
        return ["p", "q", "aic"]

    def _fit(self, y: np.ndarray):
        key = (y.tobytes(), y.shape[0])
        cached = getattr(self, "_fit_cache", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        r = y - y.mean()
        best = None
        mo = int(self.get(self.MAX_ORDER))
        for p in range(1, mo + 1):
            for q in range(0, mo + 1):
                nll, alphas, betas, omega = _garch_fit_pq(r, p, q)
                k = 1 + p + q
                aic = 2 * k + 2 * nll
                if best is None or aic < best["aic"]:
                    best = {"p": p, "q": q, "aic": aic, "nll": nll,
                            "alphas": alphas, "betas": betas,
                            "omega": omega, "r": r}
        self._fit_cache = (key, best)
        return best

    def _forecast(self, y: np.ndarray, horizon: int) -> np.ndarray:
        fit = self._fit(y)
        r = fit["r"]
        p, q = fit["p"], fit["q"]
        omega, alphas, betas = fit["omega"], fit["alphas"], fit["betas"]
        m = max(p, q, 1)
        # reconstruct conditional variances to seed the forecast recursion
        var0 = float(np.var(r)) + 1e-8
        h_hist = [var0] * m
        r2_hist = [var0] * m
        for t in range(m, len(r)):
            h_new = omega
            for i in range(p):
                h_new += alphas[i] * r2_hist[i]
            for j in range(q):
                h_new += betas[j] * h_hist[j]
            h_hist = [h_new] + h_hist[:-1]
            r2_hist = [float(r[t] ** 2)] + r2_hist[:-1]
        out = []
        for _ in range(horizon):
            h_new = omega
            for i in range(p):
                h_new += alphas[i] * r2_hist[i]
            for j in range(q):
                h_new += betas[j] * h_hist[j]
            out.append(h_new)
            h_hist = [h_new] + h_hist[:-1]
            r2_hist = [h_new] + r2_hist[:-1]  # E[r²] = h
        return np.sqrt(np.asarray(out, np.float64))

    def _extra_outputs(self, y: np.ndarray):
        fit = self._fit(y)
        return {"p": float(fit["p"]), "q": float(fit["q"]),
                "aic": float(fit["aic"])}


# ---------------------------------------------------------------------------
# lookup in timeseries
# ---------------------------------------------------------------------------


def _series_cell(cell) -> Tuple[np.ndarray, MTable]:
    if not isinstance(cell, MTable):
        raise AkIllegalDataException(
            "timeseries lookup expects an MTable series cell "
            "(time column + value column)")
    times = np.asarray(cell.col(cell.names[0]))
    return times, cell


def _parse_time(v):
    try:
        return float(v)
    except (TypeError, ValueError):
        return np.datetime64(str(v)).astype("datetime64[s]").astype(float)


class LookupValueInTimeSeriesMapper(SISOMapper):
    """Row time → value at (or latest before) that time in the row's series
    cell (reference: operator/common/timeseries/
    LookupValueInTimeSeriesMapper.java)."""

    TIME_COL = ParamInfo("timeCol", str, optional=False)

    def map_table(self, t: MTable) -> MTable:
        sel = self.get(HasSelectedCol.SELECTED_COL)
        out = self.get(HasOutputCol.OUTPUT_COL) or "lookup_value"
        time_col = self.get(self.TIME_COL)
        res = np.full(t.num_rows, np.nan)
        for i in range(t.num_rows):
            cell = t.col(sel)[i]
            if cell is None:
                continue
            times, series = _series_cell(cell)
            tv = _parse_time(t.col(time_col)[i])
            ts = np.asarray([_parse_time(x) for x in times])
            value_col = series.names[-1]
            mask = ts <= tv
            if mask.any():
                res[i] = float(np.asarray(
                    series.col(value_col))[mask][np.argmax(ts[mask])])
        return self._append_result(
            t, {out: res}, {out: AlinkTypes.DOUBLE})

    def output_schema(self, input_schema):
        out = self.get(HasOutputCol.OUTPUT_COL) or "lookup_value"
        return self._append_result_schema(input_schema, [out],
                                          [AlinkTypes.DOUBLE])

    def map_column(self, values, type_tag):  # SISOMapper API unused
        raise NotImplementedError


class LookupValueInTimeSeriesBatchOp(MapBatchOp, HasSelectedCol,
                                     HasOutputCol, HasReservedCols):
    """(reference: operator/batch/dataproc/
    LookupValueInTimeSeriesBatchOp.java)"""

    mapper_cls = LookupValueInTimeSeriesMapper
    TIME_COL = LookupValueInTimeSeriesMapper.TIME_COL


class LookupVectorInTimeSeriesMapper(LookupValueInTimeSeriesMapper):
    """Same lookup, vector-valued series (reference: operator/common/
    timeseries/LookupVectorInTimeSeriesMapper.java)."""

    def map_table(self, t: MTable) -> MTable:
        sel = self.get(HasSelectedCol.SELECTED_COL)
        out = self.get(HasOutputCol.OUTPUT_COL) or "lookup_vector"
        time_col = self.get(self.TIME_COL)
        res = np.empty(t.num_rows, object)
        for i in range(t.num_rows):
            cell = t.col(sel)[i]
            if cell is None:
                res[i] = None
                continue
            times, series = _series_cell(cell)
            tv = _parse_time(t.col(time_col)[i])
            ts = np.asarray([_parse_time(x) for x in times])
            value_col = series.names[-1]
            mask = ts <= tv
            if mask.any():
                v = np.asarray(
                    series.col(value_col), object)[mask][np.argmax(ts[mask])]
                res[i] = parse_vector(v)
            else:
                res[i] = None
        return self._append_result(
            t, {out: res}, {out: AlinkTypes.DENSE_VECTOR})

    def output_schema(self, input_schema):
        out = self.get(HasOutputCol.OUTPUT_COL) or "lookup_vector"
        return self._append_result_schema(input_schema, [out],
                                          [AlinkTypes.DENSE_VECTOR])


class LookupVectorInTimeSeriesBatchOp(MapBatchOp, HasSelectedCol,
                                      HasOutputCol, HasReservedCols):
    """(reference: operator/batch/dataproc/
    LookupVectorInTimeSeriesBatchOp.java)"""

    mapper_cls = LookupVectorInTimeSeriesMapper
    TIME_COL = LookupVectorInTimeSeriesMapper.TIME_COL


class LookupRecentDaysMapper(SISOMapper):
    """Aggregate the last N days of the row's series before the row time:
    count/sum/mean/min/max as a stat vector (reference: operator/batch/
    dataproc/LookupRecentDaysBatchOp.java)."""

    TIME_COL = ParamInfo("timeCol", str, optional=False)
    NUM_DAYS = ParamInfo("numDays", int, default=7,
                         validator=MinValidator(1))

    def map_table(self, t: MTable) -> MTable:
        sel = self.get(HasSelectedCol.SELECTED_COL)
        out = self.get(HasOutputCol.OUTPUT_COL) or "recent_stats"
        time_col = self.get(self.TIME_COL)
        span = float(self.get(self.NUM_DAYS)) * 86400.0
        res = np.empty(t.num_rows, object)
        for i in range(t.num_rows):
            cell = t.col(sel)[i]
            if cell is None:
                res[i] = None
                continue
            times, series = _series_cell(cell)
            tv = _parse_time(t.col(time_col)[i])
            ts = np.asarray([_parse_time(x) for x in times])
            vals = np.asarray(series.col(series.names[-1]), np.float64)
            mask = (ts <= tv) & (ts > tv - span)
            w = vals[mask]
            if w.size:
                res[i] = DenseVector(np.asarray(
                    [float(w.size), w.sum(), w.mean(), w.min(), w.max()]))
            else:
                res[i] = DenseVector(np.asarray([0.0, 0, 0, 0, 0]))
        return self._append_result(
            t, {out: res}, {out: AlinkTypes.DENSE_VECTOR})

    def output_schema(self, input_schema):
        out = self.get(HasOutputCol.OUTPUT_COL) or "recent_stats"
        return self._append_result_schema(input_schema, [out],
                                          [AlinkTypes.DENSE_VECTOR])

    def map_column(self, values, type_tag):
        raise NotImplementedError


class LookupRecentDaysBatchOp(MapBatchOp, HasSelectedCol, HasOutputCol,
                              HasReservedCols):
    """Recent-days feature lookup (reference:
    operator/batch/feature/LookupRecentDaysBatchOp.java — a ModelMapBatchOp
    whose MODEL table carries group keys + precomputed recent-days feature
    columns, common/dataproc/LookupRecentDaysModelMapper.java).

    Two forms:
    - 2 inputs ``(model, data)`` — the reference contract: data rows are
      decorated by key lookup into the model table (keys = ``mapKeyCols``
      or the shared column names); misses yield NULLs.
    - 1 input — self-series convenience: count/sum/mean/min/max of the
      row's own series over the trailing ``numDays`` window.
    """

    mapper_cls = LookupRecentDaysMapper
    TIME_COL = LookupRecentDaysMapper.TIME_COL
    NUM_DAYS = LookupRecentDaysMapper.NUM_DAYS
    MAP_KEY_COLS = ParamInfo("mapKeyCols", list,
                             desc="model-table key columns; default: the "
                                  "columns shared with the data table")
    FEATURE_SCHEMA_STR = ParamInfo(
        "featureSchemaStr", str,
        desc="declared schema of the looked-up feature columns")

    _min_inputs = 1
    _max_inputs = 2

    def _lookup_cols(self, model_schema, data_schema):
        keys = self.get(self.MAP_KEY_COLS) or [
            n for n in model_schema.names if n in set(data_schema.names)]
        if not keys:
            raise AkIllegalArgumentException(
                "LookupRecentDays needs mapKeyCols (no shared columns "
                "between model and data)")
        feat = self.get(self.FEATURE_SCHEMA_STR)
        if feat:
            from ...common.mtable import TableSchema

            fs = TableSchema.parse(feat)
            feats = list(zip(fs.names, fs.types))
        else:
            feats = [(n, model_schema.type_of(n))
                     for n in model_schema.names if n not in set(keys)]
        return keys, feats

    def _execute_impl(self, *ins: MTable) -> MTable:
        if len(ins) == 1:
            return super()._execute_impl(ins[0])
        model, t = ins
        keys, feats = self._lookup_cols(model.schema, t.schema)
        index: Dict[tuple, tuple] = {}
        kcols = [model.col(k) for k in keys]
        vcols = [model.col(n) for n, _ in feats]
        for i in range(model.num_rows):
            index[tuple(c[i] for c in kcols)] = tuple(c[i] for c in vcols)
        dk = [t.col(k) for k in keys]
        cols = {n: t.col(n) for n in t.names}
        types = dict(zip(t.names, t.schema.types))
        from ...common.mtable import TableSchema

        for j, (n, tp) in enumerate(feats):
            vals = []
            for i in range(t.num_rows):
                hit = index.get(tuple(c[i] for c in dk))
                vals.append(None if hit is None else hit[j])
            if AlinkTypes.is_numeric(tp):
                cols[n] = np.asarray(
                    [np.nan if v is None else float(v) for v in vals])
                types[n] = AlinkTypes.DOUBLE
            else:
                cols[n] = np.asarray(vals, object)
                types[n] = tp
        names = list(t.names) + [n for n, _ in feats]
        return MTable(cols, TableSchema(names, [types[n] for n in names]))

    def _out_schema(self, *in_schemas):
        if len(in_schemas) == 1:
            return super()._out_schema(*in_schemas)
        model_schema, data_schema = in_schemas
        keys, feats = self._lookup_cols(model_schema, data_schema)
        from ...common.mtable import TableSchema

        names = list(data_schema.names) + [n for n, _ in feats]
        types = list(data_schema.types) + [
            AlinkTypes.DOUBLE if AlinkTypes.is_numeric(tp) else tp
            for _, tp in feats]
        return TableSchema(names, types)

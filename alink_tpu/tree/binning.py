"""Quantile binning for histogram trees.

(reference: operator/common/tree/parallelcart/EpsilonApproQuantile.java — a
distributed epsilon-approximate sketch; here one exact percentile pass, since
the whole column fits a single jit reduction on the host+device.)
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


def quantile_bins(X: np.ndarray, num_bins: int = 64) -> np.ndarray:
    """Per-feature bin edges, shape (d, num_bins-1). Edges are interior
    boundaries: bin b holds x in (edge[b-1], edge[b]]."""
    qs = np.linspace(0, 100, num_bins + 1)[1:-1]
    edges = np.percentile(X, qs, axis=0).T.astype(np.float32)  # (d, B-1)
    return edges


def apply_bins(X: np.ndarray, edges: np.ndarray) -> np.ndarray:
    """Bin codes int32 (n, d): number of edges strictly below x."""
    # searchsorted per feature; vectorized over features
    n, d = X.shape
    out = np.empty((n, d), np.int32)
    for j in range(d):
        out[:, j] = np.searchsorted(edges[j], X[:, j], side="left")
    return out

"""Train logistic regression on 1M-dimensional sparse vectors without ever
densifying (the HugeSparseVector capability; ELL SparseBlock path)."""

import numpy as np

from alink_tpu.common.linalg import SparseVector
from alink_tpu.common.mtable import MTable, TableSchema
from alink_tpu.operator.batch import (LogisticRegressionPredictBatchOp,
                                      LogisticRegressionTrainBatchOp)
from alink_tpu.operator.batch.base import TableSourceBatchOp

rng = np.random.default_rng(0)
d = 1_000_000
cells, labels = [], []
for _ in range(300):
    label = int(rng.integers(2))
    idx = np.sort(rng.choice(d, size=8, replace=False))
    val = rng.normal(size=8)
    val[0] = (1.0 if label else -1.0) + 0.1 * rng.normal()
    idx[0] = 0
    cells.append(SparseVector(d, np.sort(idx), val))
    labels.append(label)

t = MTable({"vec": np.asarray(cells, object),
            "label": np.asarray(labels, np.int64)},
           TableSchema(["vec", "label"], ["SPARSE_VECTOR", "LONG"]))
src = TableSourceBatchOp(t)
model = LogisticRegressionTrainBatchOp(
    vectorCol="vec", labelCol="label", maxIter=20,
    standardization=False).link_from(src)
out = LogisticRegressionPredictBatchOp(vectorCol="vec") \
    .link_from(model, src).collect()
acc = (np.asarray(out.col("pred")) == np.asarray(labels)).mean()
print(f"1M-dim sparse logistic accuracy: {acc:.3f}")

"""Recommendation family tests (reference model: AlsTrainBatchOpTest,
ItemCfTrainBatchOpTest, SwingTrainBatchOpTest + RecommKernel serving tests)."""

import json

import numpy as np

from alink_tpu.common.mtable import AlinkTypes, MTable
from alink_tpu.operator.batch import (
    AlsItemsPerUserRecommBatchOp,
    AlsRateRecommBatchOp,
    AlsSimilarItemsRecommBatchOp,
    AlsTrainBatchOp,
    AlsUsersPerItemRecommBatchOp,
    ItemCfItemsPerUserRecommBatchOp,
    ItemCfRateRecommBatchOp,
    ItemCfSimilarItemsRecommBatchOp,
    ItemCfTrainBatchOp,
    SwingSimilarItemsRecommBatchOp,
    SwingTrainBatchOp,
    TableSourceBatchOp,
    UserCfRateRecommBatchOp,
    UserCfTrainBatchOp,
)


def _low_rank_ratings(n_u=40, n_i=30, k=4, seed=0, keep=0.6):
    """Observed entries of a rank-k matrix, plus the full ground truth."""
    rng = np.random.RandomState(seed)
    U = rng.randn(n_u, k) / np.sqrt(k)
    V = rng.randn(n_i, k) / np.sqrt(k)
    M = U @ V.T
    mask = rng.rand(n_u, n_i) < keep
    us, is_ = np.nonzero(mask)
    return us, is_, M[us, is_], M


def test_als_recovers_low_rank():
    us, is_, r, M = _low_rank_ratings()
    t = MTable({"user": us.astype(np.int64), "item": is_.astype(np.int64),
                "rating": r})
    src = TableSourceBatchOp(t)
    train = AlsTrainBatchOp(
        userCol="user", itemCol="item", rateCol="rating",
        rank=4, numIter=15, **{"lambda": 0.01},
    ).link_from(src)
    pred = AlsRateRecommBatchOp(predictionCol="p").link_from(train, src)
    out = pred.collect()
    rmse = float(np.sqrt(np.mean(
        (np.asarray(out.col("p")) - r) ** 2
    )))
    assert rmse < 0.08, rmse
    # held-out entries reconstruct too (generalization, not memorization)
    held_u, held_i = np.nonzero(np.ones_like(M, dtype=bool))
    ht = MTable({"user": held_u.astype(np.int64),
                 "item": held_i.astype(np.int64)})
    hp = AlsRateRecommBatchOp(predictionCol="p").link_from(
        train, TableSourceBatchOp(ht)
    ).collect()
    rmse_all = float(np.sqrt(np.nanmean(
        (np.asarray(hp.col("p")) - M[held_u, held_i]) ** 2
    )))
    assert rmse_all < 0.25, rmse_all


def test_als_implicit_ranks_positives():
    rng = np.random.RandomState(1)
    # two user groups, two item groups; users interact within their group
    us, is_ = [], []
    for u in range(20):
        grp = u % 2
        for i in range(15):
            if i % 2 == grp and rng.rand() < 0.8:
                us.append(u)
                is_.append(i)
    t = MTable({"user": np.asarray(us, np.int64),
                "item": np.asarray(is_, np.int64)})
    src = TableSourceBatchOp(t)
    train = AlsTrainBatchOp(
        userCol="user", itemCol="item", rank=4, numIter=10,
        implicitPrefs=True, alpha=20.0, **{"lambda": 0.05},
    ).link_from(src)
    rec = AlsItemsPerUserRecommBatchOp(predictionCol="rec", k=5).link_from(
        train, TableSourceBatchOp(MTable({"user": np.arange(4, dtype=np.int64)}))
    ).collect()
    for row, user in zip(rec.col("rec"), range(4)):
        items = json.loads(row)["object"]
        assert items, "no recommendations"
        grp_match = sum(1 for i in items if i % 2 == user % 2)
        assert grp_match >= len(items) * 0.6, (user, items)


def test_als_topk_and_similar_ops():
    us, is_, r, _ = _low_rank_ratings(20, 12, 3, seed=2)
    t = MTable({"user": us.astype(np.int64), "item": is_.astype(np.int64),
                "rating": r})
    train = AlsTrainBatchOp(
        userCol="user", itemCol="item", rateCol="rating", rank=3, numIter=5,
    ).link_from(TableSourceBatchOp(t))
    users = MTable({"user": np.asarray([0, 1, 999], np.int64)})
    rec = AlsItemsPerUserRecommBatchOp(predictionCol="rec", k=4).link_from(
        train, TableSourceBatchOp(users)
    ).collect()
    assert rec.schema.type_of("rec") == AlinkTypes.STRING
    d0 = json.loads(rec.col("rec")[0])
    assert len(d0["object"]) == 4 and len(d0["rate"]) == 4
    assert json.loads(rec.col("rec")[2])["object"] == []  # unknown user

    items = MTable({"item": np.asarray([0, 5], np.int64)})
    upi = AlsUsersPerItemRecommBatchOp(predictionCol="rec", k=3).link_from(
        train, TableSourceBatchOp(items)
    ).collect()
    assert len(json.loads(upi.col("rec")[0])["object"]) == 3

    sim = AlsSimilarItemsRecommBatchOp(predictionCol="rec", k=3).link_from(
        train, TableSourceBatchOp(items)
    ).collect()
    d = json.loads(sim.col("rec")[0])
    assert 0 not in d["object"] and len(d["object"]) == 3


def test_item_cf_rate_and_topk():
    # item 0 and 1 co-rated by everyone, item 2 by nobody who rated 0
    users = np.repeat(np.arange(8), 2)
    items = np.tile([0, 1], 8)
    users = np.concatenate([users, [8, 8]])
    items = np.concatenate([items, [2, 3]])
    rates = np.ones(len(users))
    t = MTable({"u": users.astype(np.int64), "i": items.astype(np.int64),
                "r": rates})
    train = ItemCfTrainBatchOp(userCol="u", itemCol="i", rateCol="r"
                               ).link_from(TableSourceBatchOp(t))
    sim = ItemCfSimilarItemsRecommBatchOp(
        predictionCol="rec", k=2, itemCol="i"
    ).link_from(train, TableSourceBatchOp(
        MTable({"i": np.asarray([0], np.int64)})
    )).collect()
    d = json.loads(sim.col("rec")[0])
    assert d["object"][0] == 1  # strongest co-occurrence

    pairs = MTable({"u": np.asarray([0, 0], np.int64),
                    "i": np.asarray([1, 2], np.int64)})
    rate = ItemCfRateRecommBatchOp(predictionCol="p").link_from(
        train, TableSourceBatchOp(pairs)
    ).collect()
    p = np.asarray(rate.col("p"))
    assert p[0] > 0  # item 1 similar to user 0's history
    assert np.isnan(p[1]) or p[1] == 0  # item 2 unrelated

    topk = ItemCfItemsPerUserRecommBatchOp(
        predictionCol="rec", k=3, userCol="u"
    ).link_from(train, TableSourceBatchOp(
        MTable({"u": np.asarray([0], np.int64)})
    )).collect()
    d = json.loads(topk.col("rec")[0])
    assert 0 not in d["object"] and 1 not in d["object"]  # seen items excluded


def test_user_cf_rate():
    users = np.repeat(np.arange(6), 3)
    items = np.tile([0, 1, 2], 6)
    rng = np.random.RandomState(3)
    rates = np.where(users % 2 == 0, 5.0, 1.0) + rng.rand(len(users)) * 0.1
    t = MTable({"u": users.astype(np.int64), "i": items.astype(np.int64),
                "r": rates})
    train = UserCfTrainBatchOp(userCol="u", itemCol="i", rateCol="r"
                               ).link_from(TableSourceBatchOp(t))
    pairs = MTable({"u": np.asarray([0], np.int64),
                    "i": np.asarray([0], np.int64)})
    out = UserCfRateRecommBatchOp(predictionCol="p").link_from(
        train, TableSourceBatchOp(pairs)
    ).collect()
    assert np.isfinite(out.col("p")[0])


def test_swing_similarity():
    # items 0,1 share many user pairs; item 2 isolated
    users, items = [], []
    for u in range(6):
        users += [u, u]
        items += [0, 1]
    users += [6]
    items += [2]
    t = MTable({"u": np.asarray(users, np.int64),
                "i": np.asarray(items, np.int64)})
    train = SwingTrainBatchOp(userCol="u", itemCol="i").link_from(
        TableSourceBatchOp(t)
    )
    sim = SwingSimilarItemsRecommBatchOp(
        predictionCol="rec", k=2, itemCol="i"
    ).link_from(train, TableSourceBatchOp(
        MTable({"i": np.asarray([0, 2], np.int64)})
    )).collect()
    d0 = json.loads(sim.col("rec")[0])
    assert d0["object"] == [1]
    assert json.loads(sim.col("rec")[1])["object"] == []  # isolated item


def test_als_pipeline_and_persistence(tmp_path):
    from alink_tpu.pipeline import ALS, Pipeline

    us, is_, r, _ = _low_rank_ratings(15, 10, 3, seed=4)
    t = MTable({"user": us.astype(np.int64), "item": is_.astype(np.int64),
                "rating": r})
    est = ALS(userCol="user", itemCol="item", rateCol="rating",
              rank=3, numIter=20, predictionCol="p", **{"lambda": 0.01})
    model = Pipeline(est).fit(t)
    out = model.transform(t).collect()
    rmse = float(np.sqrt(np.mean((np.asarray(out.col("p")) - r) ** 2)))
    assert rmse < 0.15, rmse
    path = str(tmp_path / "als_pipe.ak")
    model.save(path)
    from alink_tpu.pipeline import PipelineModel

    loaded = PipelineModel.load(path)
    out2 = loaded.transform(t).collect()
    np.testing.assert_allclose(
        np.asarray(out2.col("p")), np.asarray(out.col("p")), rtol=1e-5
    )


def test_item_cf_jaccard():
    users = np.repeat(np.arange(8), 2)
    items = np.tile([0, 1], 8)
    t = MTable({"u": users.astype(np.int64), "i": items.astype(np.int64)})
    train = ItemCfTrainBatchOp(
        userCol="u", itemCol="i", similarityType="jaccard"
    ).link_from(TableSourceBatchOp(t))
    sim = ItemCfSimilarItemsRecommBatchOp(
        predictionCol="rec", k=1, itemCol="i"
    ).link_from(train, TableSourceBatchOp(
        MTable({"i": np.asarray([0], np.int64)})
    )).collect()
    d = json.loads(sim.col("rec")[0])
    assert d["object"] == [1]
    assert abs(d["rate"][0] - 1.0) < 1e-6  # identical user sets -> jaccard 1

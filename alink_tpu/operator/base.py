"""Operator DAG API — the framework's L3.

Capability parity with the reference's operator layer (reference:
core/src/main/java/com/alibaba/alink/operator/AlgoOperator.java:29,
operator/batch/BatchOperator.java:67 — ``link``/``linkFrom`` DAG building,
deferred execution triggered by ``execute``/``collect``/``print``, lazy sinks at
BatchOperator.java:688-725, side outputs).

Re-design: the DAG is a host-side graph of Python operator nodes over columnar
:class:`MTable` values. Evaluation is pull-based and memoized — ``collect()``
walks the upstream graph once, runs each node's ``_execute_impl`` (whose heavy
math is jit-compiled JAX over device meshes), caches results, and flushes every
pending lazy sink in the session, preserving the reference's "one job runs all
pending sinks" contract without a Flink scheduler.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional, Sequence, Tuple

import numpy as np

from ..common.env import MLEnvironmentFactory
from ..common.exceptions import (
    AkIllegalArgumentException,
    AkIllegalOperationException,
    AkIllegalStateException,
)
from ..common.mtable import MTable, TableSchema
from ..common.params import ParamInfo, WithParams


class AlgoOperator(WithParams):
    """Base of Batch/Stream/Local operators: a DAG node producing one output
    table and optional side-output tables."""

    ML_ENVIRONMENT_ID = ParamInfo(
        "MLEnvironmentId", int, default=0, desc="session id of the MLEnvironment"
    )

    def __init__(self, params=None, **kwargs):
        super().__init__(params, **kwargs)
        self._inputs: List[AlgoOperator] = []
        self._output: Optional[MTable] = None
        self._side_tables: List[MTable] = []
        self._executed = False
        # per-op lock: concurrent lazy-sink evaluation (AlinkLocalSession
        # thread pool) may reach shared upstream nodes from several threads;
        # DAG acyclicity makes the per-edge lock order deadlock-free
        self._eval_lock = threading.RLock()

    # -- environment -------------------------------------------------------
    @property
    def env(self):
        return MLEnvironmentFactory.get(self.get(AlgoOperator.ML_ENVIRONMENT_ID))

    # -- DAG building ------------------------------------------------------
    def link_from(self, *inputs: "AlgoOperator") -> "AlgoOperator":
        self.check_op_size(len(inputs))
        self._inputs = list(inputs)
        self._executed = False
        self._output = None
        return self

    linkFrom = link_from

    def link(self, next_op: "AlgoOperator") -> "AlgoOperator":
        return next_op.link_from(self)

    # number of expected inputs; None = variadic
    _min_inputs: Optional[int] = None
    _max_inputs: Optional[int] = None

    def check_op_size(self, n: int):
        lo = self._min_inputs
        hi = self._max_inputs
        if lo is not None and n < lo:
            raise AkIllegalOperationException(
                f"{type(self).__name__} expects >= {lo} inputs, got {n}"
            )
        if hi is not None and n > hi:
            raise AkIllegalOperationException(
                f"{type(self).__name__} expects <= {hi} inputs, got {n}"
            )

    # -- execution ---------------------------------------------------------
    def _execute_impl(self, *inputs: MTable):
        """Compute this node. Return an MTable, or (MTable, [side MTables])."""
        raise NotImplementedError(type(self).__name__)

    def _evaluate(self) -> MTable:
        """Serial, memoized pull-evaluation of this node (and recursively its
        upstreams). The pipelined engine (common/executor.py) schedules whole
        sub-DAGs and then reads results back through this same method, so the
        exactly-once contract lives in one place."""
        with self._eval_lock:
            if not self._executed:
                ins = [op._evaluate() for op in self._inputs]
                result = self._execute_impl(*ins)
                if isinstance(result, tuple):
                    self._output, sides = result
                    self._side_tables = list(sides)
                else:
                    self._output = result
                    self._side_tables = []
                self._executed = True
            return self._output

    def _set_result(self, table: MTable, sides: Sequence[MTable] = ()):
        """Install an externally computed result (fused mapper chains write
        the chain tail this way), preserving the memoization contract."""
        with self._eval_lock:
            if not self._executed:
                self._output = table
                self._side_tables = list(sides)
                self._executed = True

    def _flush_lazy(self, extra_roots: Sequence["AlgoOperator"] = ()):
        # the pipelined DAG engine schedules every pending sink (plus any
        # extra roots) as one topological job: independent branches run
        # concurrently on the session's DAG pool, linear mapper runs fuse,
        # and shared upstreams stay exactly-once via the per-op eval lock
        from ..common.executor import run_dag

        mgr = self.env.lazy_manager
        pending = list(mgr.pending_ops())
        roots = list(extra_roots) + pending
        if roots:
            # opt-in pre-flight (ALINK_VALIDATE_PLAN=warn|error): propagate
            # static schemas over the whole deferred DAG before any kernel
            # traces; `error` raises on error-severity diagnostics, `warn`
            # logs + counts them and never changes results
            from ..analysis import preflight

            preflight(roots, where="execute")
        try:
            run_dag(self.env, roots)
        except BaseException:
            # graceful degradation on a failed run: sinks whose branches
            # DID complete still fire and clear, while failed branches stay
            # pending — a later execute()/collect() re-plans only the
            # unfinished sub-DAG (successful upstreams remain memoized).
            # A raising sink callback must not mask the run's failure (or
            # starve its sibling sinks): callback errors are counted and
            # the original exception propagates unchanged.
            from ..common.metrics import metrics

            for op in pending:
                if op._executed:
                    try:
                        mgr.fill(op, op._evaluate())
                    except Exception:
                        metrics.incr("resilience.sink_callback_errors")
            raise
        for op in pending:
            mgr.fill(op, op._evaluate())

    # -- results -----------------------------------------------------------
    def get_output_table(self) -> MTable:
        return self._evaluate()

    def get_side_output(self, index: int) -> "AlgoOperator":
        return SideOutputOp(self, index)

    def get_side_output_count(self) -> int:
        self._evaluate()
        return len(self._side_tables)

    # -- static schema derivation ------------------------------------------
    # The reference computes output schemas at DAG-build time (reference:
    # Mapper.prepareIoSchema, TableUtil schema derivation). Accessing
    # ``op.schema`` on an unexecuted chain must therefore never run the job.
    def _out_schema(self, *in_schemas: TableSchema) -> TableSchema:
        """Static output schema given the input schemas.

        Default: probe ``_execute_impl`` with zero-row, correctly-typed
        inputs — row-wise relational ops derive their schema for free this
        way. Ops whose empty-input execution is expensive, impossible
        (trainers), or side-effectful (sinks) MUST override."""
        return self._schema_probe(*in_schemas)[0]

    def _side_schemas(self, *in_schemas: TableSchema) -> List[TableSchema]:
        """Static schemas of the side outputs (same probe strategy)."""
        return self._schema_probe(*in_schemas)[1]

    def _schema_probe(self, *in_schemas: TableSchema):
        key = tuple(s.to_str() for s in in_schemas)
        cached = getattr(self, "_probe_cache", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        empties = [MTable.empty(s) for s in in_schemas]
        try:
            result = self._execute_impl(*empties)
        except Exception as e:
            raise AkIllegalOperationException(
                f"{type(self).__name__} cannot derive a static schema "
                f"(zero-row probe failed: {e!r}); override _out_schema"
            ) from e
        if isinstance(result, tuple):
            main, sides = result
            out = (main.schema, [s.schema for s in sides])
        else:
            out = (result.schema, [])
        self._probe_cache = (key, out)
        return out

    def _static_schema(self) -> TableSchema:
        if self._executed:
            return self._output.schema
        return self._out_schema(*[op._static_schema() for op in self._inputs])

    def _static_model_meta(self) -> "dict | None":
        """Meta dict of the model table this op will produce, derivable
        without executing — model-producing ops override with the subset of
        keys their paired ModelMapper needs for schema decisions (labelType
        etc.). None = this op does not statically declare model meta."""
        if self._executed and self._output is not None:
            from ..common.model import MODEL_SCHEMA, table_to_model

            if self._output.schema == MODEL_SCHEMA:
                return table_to_model(self._output)[0]
        return None

    @property
    def schema(self) -> TableSchema:
        return self._static_schema()

    def get_col_names(self) -> List[str]:
        return self.schema.names

    def get_col_types(self) -> List[str]:
        return list(self.schema.types)

    def collect(self) -> MTable:
        self._flush_lazy(extra_roots=[self])
        return self._evaluate()

    def collect_to_dataframe(self):
        return self.collect().to_dataframe()

    def first_n(self, n: int) -> MTable:
        return self.collect().head(n)

    def print(self, n: int = 20, title: Optional[str] = None) -> "AlgoOperator":
        t = self.collect()
        if title:
            print(title)
        print(t.to_display_string(max_rows=n))
        return self

    # -- lazy sinks --------------------------------------------------------
    def lazy_collect(self, *callbacks: Callable[[MTable], None]) -> "AlgoOperator":
        lazy = self.env.lazy_manager.gen_lazy(self)
        for cb in callbacks:
            lazy.add_callback(cb)
        return self

    def lazy_print(self, n: int = 20, title: Optional[str] = None) -> "AlgoOperator":
        def _print(t: MTable):
            if title:
                print(title)
            print(t.to_display_string(max_rows=n))

        return self.lazy_collect(_print)

    def execute(self):
        """Force all pending lazy sinks in this session (reference:
        BatchOperator.execute → triggerLazyEvaluation, BatchOperator.java:316-330)."""
        self._flush_lazy()

    # -- SQL-ish sugar (reference: AlgoOperator select/filter/groupBy/orderBy) --
    def select(self, fields: "str | Sequence[str]") -> "AlgoOperator":
        from .sql import SelectOp

        return SelectOp(fields).link_from(self)

    def filter(self, predicate: str) -> "AlgoOperator":
        from .sql import FilterOp

        return FilterOp(predicate).link_from(self)

    where = filter

    def distinct(self) -> "AlgoOperator":
        from .sql import DistinctOp

        return DistinctOp().link_from(self)

    def order_by(self, field: str, limit: Optional[int] = None, ascending: bool = True):
        from .sql import OrderByOp

        return OrderByOp(field, limit, ascending).link_from(self)

    orderBy = order_by

    def group_by(self, group_cols: str, select_clause: str) -> "AlgoOperator":
        from .sql import GroupByOp

        return GroupByOp(group_cols, select_clause).link_from(self)

    groupBy = group_by

    def sample(self, ratio: float, seed: int = 0) -> "AlgoOperator":
        from .sql import SampleOp

        return SampleOp(ratio, seed).link_from(self)

    def rename(self, mapping) -> "AlgoOperator":
        from .sql import RenameOp

        return RenameOp(mapping).link_from(self)

    def apply_func(
        self,
        fn: Callable[[MTable], MTable],
        name: str = "apply_func",
        out_schema: "TableSchema | str | None" = None,
    ) -> "AlgoOperator":
        """Escape hatch: arbitrary MTable→MTable host function as a DAG node
        (reference: udf/udtf ops). ``out_schema`` declares the result schema
        for static derivation (like the reference's UDF result types)."""
        return _FuncOp(fn, name, out_schema).link_from(self)

    def __repr__(self):
        state = "executed" if self._executed else "deferred"
        return f"{type(self).__name__}({state})"


class SideOutputOp(AlgoOperator):
    """Materialized view of a parent's i-th side output
    (reference: BatchOperator.getSideOutput)."""

    def __init__(self, parent: AlgoOperator, index: int):
        super().__init__()
        self._parent = parent
        self._index = index
        self._inputs = [parent]

    def _execute_impl(self, parent_out: MTable) -> MTable:
        sides = self._parent._side_tables
        if self._index >= len(sides):
            raise AkIllegalArgumentException(
                f"side output {self._index} out of range ({len(sides)} available)"
            )
        return sides[self._index]

    def _static_schema(self) -> TableSchema:
        # bypass the parent's *main* schema: only the side schemas are needed
        if self._executed:
            return self._output.schema
        if self._parent._executed:
            return self._parent._side_tables[self._index].schema
        grand = [op._static_schema() for op in self._parent._inputs]
        sides = self._parent._side_schemas(*grand)
        if self._index >= len(sides):
            raise AkIllegalArgumentException(
                f"side output {self._index} out of range ({len(sides)} declared)"
            )
        return sides[self._index]


class _FuncOp(AlgoOperator):
    _min_inputs = 1

    def __init__(self, fn, name, out_schema: "TableSchema | str | None" = None):
        super().__init__()
        self._fn = fn
        self._name = name
        if isinstance(out_schema, str):
            out_schema = TableSchema.parse(out_schema)
        self._declared_schema = out_schema

    def _execute_impl(self, *inputs: MTable) -> MTable:
        return self._fn(*inputs)

    def _out_schema(self, *in_schemas: TableSchema) -> TableSchema:
        if self._declared_schema is not None:
            return self._declared_schema
        # UDFs without a declared schema fall back to the zero-row probe
        return super()._out_schema(*in_schemas)


class TableSourceOp(AlgoOperator):
    """Wrap an existing MTable as a source node (reference:
    operator/batch/source/TableSourceBatchOp.java)."""

    _max_inputs = 0

    def __init__(self, table: MTable, **kwargs):
        super().__init__(**kwargs)
        self._table = table

    def _execute_impl(self) -> MTable:
        return self._table

    def _out_schema(self) -> TableSchema:
        return self._table.schema

    def _static_model_meta(self):
        from ..common.model import MODEL_SCHEMA, table_to_model

        if self._table.schema == MODEL_SCHEMA:
            return table_to_model(self._table)[0]
        return None

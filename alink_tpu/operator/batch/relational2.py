"""Relational long-tail: outer joins, multiset set-ops, exact-size samples,
rename, print, wrappers.

Capability parity (reference: operator/batch/sql/LeftOuterJoinBatchOp.java,
RightOuterJoinBatchOp.java, FullOuterJoinBatchOp.java,
IntersectAllBatchOp.java, MinusAllBatchOp.java, AsBatchOp.java,
dataproc/SampleWithSizeBatchOp.java, StratifiedSampleWithSizeBatchOp.java,
utils/PrintBatchOp.java, utils/DataSetWrapperBatchOp.java,
source/RandomVectorSourceBatchOp.java).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ...common.exceptions import AkIllegalArgumentException
from ...common.linalg import DenseVector
from ...common.mtable import AlinkTypes, MTable, TableSchema
from ...common.params import MinValidator, ParamInfo, RangeValidator
from .base import BatchOperator, TableSourceBatchOp
from . import JoinBatchOp


class LeftOuterJoinBatchOp(JoinBatchOp):
    """(reference: operator/batch/sql/LeftOuterJoinBatchOp.java)"""

    def __init__(self, join_predicate: str = None, select_clause: str = "*",
                 **kw):
        kw.pop("how", None)
        pred = join_predicate or kw.pop("joinPredicate", None)
        super().__init__(pred, select_clause, how="left", **kw)


class RightOuterJoinBatchOp(JoinBatchOp):
    """(reference: operator/batch/sql/RightOuterJoinBatchOp.java)"""

    def __init__(self, join_predicate: str = None, select_clause: str = "*",
                 **kw):
        kw.pop("how", None)
        pred = join_predicate or kw.pop("joinPredicate", None)
        super().__init__(pred, select_clause, how="right", **kw)


class FullOuterJoinBatchOp(JoinBatchOp):
    """(reference: operator/batch/sql/FullOuterJoinBatchOp.java)"""

    def __init__(self, join_predicate: str = None, select_clause: str = "*",
                 **kw):
        kw.pop("how", None)
        pred = join_predicate or kw.pop("joinPredicate", None)
        super().__init__(pred, select_clause, how="full", **kw)


class IntersectAllBatchOp(BatchOperator):
    """INTERSECT ALL: keep min(count_left, count_right) copies of each row
    (reference: operator/batch/sql/IntersectAllBatchOp.java)."""

    _min_inputs = 2
    _max_inputs = 2

    def _execute_impl(self, a: MTable, b: MTable) -> MTable:
        from collections import Counter

        rows_b = Counter(tuple(r) for r in b.rows())
        keep = np.zeros(a.num_rows, bool)
        for i, r in enumerate(a.rows()):
            k = tuple(r)
            if rows_b.get(k, 0) > 0:
                rows_b[k] -= 1
                keep[i] = True
        return a.filter_mask(keep)

    def _out_schema(self, a, b):
        return a


class MinusAllBatchOp(BatchOperator):
    """EXCEPT ALL: subtract per-occurrence counts (reference:
    operator/batch/sql/MinusAllBatchOp.java)."""

    _min_inputs = 2
    _max_inputs = 2

    def _execute_impl(self, a: MTable, b: MTable) -> MTable:
        from collections import Counter

        rows_b = Counter(tuple(r) for r in b.rows())
        keep = np.ones(a.num_rows, bool)
        for i, r in enumerate(a.rows()):
            k = tuple(r)
            if rows_b.get(k, 0) > 0:
                rows_b[k] -= 1
                keep[i] = False
        return a.filter_mask(keep)

    def _out_schema(self, a, b):
        return a


class AsBatchOp(BatchOperator):
    """Rename ALL columns positionally: ``as("a, b, c")`` (reference:
    operator/batch/sql/AsBatchOp.java)."""

    CLAUSE = ParamInfo("clause", str, optional=False, aliases=("fields",))

    _min_inputs = 1
    _max_inputs = 1

    def _names(self):
        return [c.strip() for c in self.get(self.CLAUSE).split(",")
                if c.strip()]

    def _execute_impl(self, t: MTable) -> MTable:
        names = self._names()
        if len(names) != len(t.names):
            raise AkIllegalArgumentException(
                f"AS clause has {len(names)} names for {len(t.names)} cols")
        return t.rename(dict(zip(t.names, names)))

    def _out_schema(self, in_schema):
        return TableSchema(self._names(), list(in_schema.types))


class SampleWithSizeBatchOp(BatchOperator):
    """Exact-size random sample, with or without replacement (reference:
    operator/batch/dataproc/SampleWithSizeBatchOp.java)."""

    SIZE = ParamInfo("size", int, optional=False,
                     aliases=("sampleSize", "numSamples"),
                     validator=MinValidator(1))
    WITH_REPLACEMENT = ParamInfo("withReplacement", bool, default=False)
    SEED = ParamInfo("randomSeed", int, default=0, aliases=("seed",))

    _min_inputs = 1
    _max_inputs = 1

    def _execute_impl(self, t: MTable) -> MTable:
        rng = np.random.default_rng(self.get(self.SEED))
        k = int(self.get(self.SIZE))
        n = t.num_rows
        if self.get(self.WITH_REPLACEMENT):
            idx = rng.integers(0, n, size=k)
        else:
            idx = rng.permutation(n)[:min(k, n)]
        return t.take(np.sort(idx))

    def _out_schema(self, in_schema):
        return in_schema


class StratifiedSampleWithSizeBatchOp(BatchOperator):
    """Exact per-stratum sample sizes: ``strataSizes="a:10,b:20"``
    (reference: operator/batch/dataproc/
    StratifiedSampleWithSizeBatchOp.java)."""

    STRATA_COL = ParamInfo("strataCol", str, optional=False)
    STRATA_SIZE = ParamInfo("strataSize", int, default=-1,
                            desc="uniform per-stratum size when >0")
    STRATA_SIZES = ParamInfo("strataSizes", str, default=None,
                             desc="per-value sizes 'v1:n1,v2:n2'")
    SEED = ParamInfo("randomSeed", int, default=0, aliases=("seed",))

    _min_inputs = 1
    _max_inputs = 1

    def _execute_impl(self, t: MTable) -> MTable:
        rng = np.random.default_rng(self.get(self.SEED))
        col = np.asarray(t.col(self.get(self.STRATA_COL)), object).astype(str)
        sizes = {}
        if self.get(self.STRATA_SIZES):
            for part in self.get(self.STRATA_SIZES).split(","):
                k, v = part.split(":")
                sizes[k.strip()] = int(v)
        default = int(self.get(self.STRATA_SIZE))
        picks: List[np.ndarray] = []
        for val in np.unique(col):
            rows = np.nonzero(col == val)[0]
            k = sizes.get(str(val), default)
            if k < 0:
                raise AkIllegalArgumentException(
                    f"no size declared for stratum {val!r}")
            picks.append(rng.permutation(rows)[:min(k, rows.size)])
        idx = np.sort(np.concatenate(picks)) if picks else np.asarray([], int)
        return t.take(idx)

    def _out_schema(self, in_schema):
        return in_schema


class PrintBatchOp(BatchOperator):
    """Print rows and pass the table through (reference:
    operator/batch/utils/PrintBatchOp.java)."""

    NUM_ROWS = ParamInfo("numRows", int, default=20)

    _min_inputs = 1
    _max_inputs = 1

    def _execute_impl(self, t: MTable) -> MTable:
        print(t.to_display_string(max_rows=self.get(self.NUM_ROWS)))
        return t

    def _out_schema(self, in_schema):
        return in_schema


class DataSetWrapperBatchOp(TableSourceBatchOp):
    """Wrap an in-memory MTable as an operator (reference:
    operator/batch/utils/DataSetWrapperBatchOp.java — the DataSet→op
    bridge; here MTable IS the dataset)."""


class RandomVectorSourceBatchOp(BatchOperator):
    """Random dense-vector table (reference:
    operator/batch/source/RandomVectorSourceBatchOp.java)."""

    NUM_ROWS = ParamInfo("numRows", int, default=100,
                         validator=MinValidator(1))
    SIZE = ParamInfo("size", list, default=[3],
                     desc="vector dims, e.g. [8]")
    SPARSITY = ParamInfo("sparsity", float, default=1.0,
                         validator=RangeValidator(0.0, 1.0))
    ID_COL = ParamInfo("idCol", str, default="alink_id")
    OUTPUT_COL = ParamInfo("outputCol", str, default="vec")
    SEED = ParamInfo("randomSeed", int, default=0, aliases=("seed",))

    _max_inputs = 0

    def _execute_impl(self) -> MTable:
        rng = np.random.default_rng(self.get(self.SEED))
        n = self.get(self.NUM_ROWS)
        dims = int(np.prod([int(s) for s in self.get(self.SIZE)]))
        vals = rng.random((n, dims))
        mask = rng.random((n, dims)) < self.get(self.SPARSITY)
        vecs = np.empty(n, object)
        for i in range(n):
            vecs[i] = DenseVector(np.where(mask[i], vals[i], 0.0))
        return MTable(
            {self.get(self.ID_COL): np.arange(n, dtype=np.int64),
             self.get(self.OUTPUT_COL): vecs},
            self._out_schema())

    def _out_schema(self) -> TableSchema:
        return TableSchema(
            [self.get(self.ID_COL), self.get(self.OUTPUT_COL)],
            [AlinkTypes.LONG, AlinkTypes.DENSE_VECTOR])

"""Timeseries train/predict pairs, AutoGarch, and in-series lookups
(reference test model: DeepARTrainBatchOpTest.java /
AutoGarchBatchOpTest.java styles)."""

import numpy as np
import pytest

from alink_tpu.common.mtable import AlinkTypes, MTable, TableSchema
from alink_tpu.operator.batch.base import TableSourceBatchOp


def _seasonal(n=120, seed=0):
    rng = np.random.default_rng(seed)
    return (10 + 3 * np.sin(np.arange(n) * 2 * np.pi / 12)
            + rng.normal(0, 0.2, n))


def test_deepar_train_predict_roundtrip(tmp_path):
    from alink_tpu.io.ak import read_ak, write_ak
    from alink_tpu.operator.batch import (
        DeepARPredictBatchOp,
        DeepARTrainBatchOp,
    )

    y = _seasonal()
    src = TableSourceBatchOp(MTable({"v": y}))
    model = DeepARTrainBatchOp(valueCol="v", numEpochs=15,
                               lookback=12).link_from(src)
    # model survives .ak persistence
    path = str(tmp_path / "deepar.ak")
    write_ak(path, model.collect())
    restored = TableSourceBatchOp(read_ak(path))
    hist = MTable(
        {"h": np.asarray([" ".join(map(str, y[-24:]))], object)},
        TableSchema(["h"], [AlinkTypes.DENSE_VECTOR]))
    out = DeepARPredictBatchOp(
        selectedCol="h", outputCol="fc", predictNum=6).link_from(
        restored, TableSourceBatchOp(hist)).collect()
    fc = out.col("fc")[0].data
    assert fc.shape == (6,)
    assert np.all(np.abs(fc - 10.0) < 6.0)  # stays in the series range


def test_lstnet_train_predict():
    from alink_tpu.operator.batch import (
        LSTNetPredictBatchOp,
        LSTNetTrainBatchOp,
    )

    y = _seasonal()
    src = TableSourceBatchOp(MTable({"v": y}))
    model = LSTNetTrainBatchOp(valueCol="v", numEpochs=15,
                               lookback=12).link_from(src)
    hist = MTable(
        {"h": np.asarray([" ".join(map(str, y[-24:]))], object)},
        TableSchema(["h"], [AlinkTypes.DENSE_VECTOR]))
    out = LSTNetPredictBatchOp(
        selectedCol="h", outputCol="fc", predictNum=4).link_from(
        model, TableSourceBatchOp(hist)).collect()
    assert out.col("fc")[0].data.shape == (4,)


def test_autogarch_picks_order():
    from alink_tpu.operator.batch import AutoGarchBatchOp

    rng = np.random.default_rng(1)
    # volatility-clustered returns
    h = 1.0
    r = []
    for _ in range(400):
        h = 0.1 + 0.3 * (r[-1] ** 2 if r else 1.0) + 0.5 * h
        r.append(rng.normal(0, np.sqrt(h)))
    out = AutoGarchBatchOp(valueCol="v", predictNum=4).link_from(
        TableSourceBatchOp(MTable({"v": np.asarray(r)}))).collect()
    row = list(out.rows())[0]
    names = out.names
    assert "p" in names and "q" in names and "aic" in names
    fc = out.col("forecast")[0].data
    assert fc.shape == (4,) and np.all(fc > 0)  # volatility is positive


def test_timeseries_lookups():
    from alink_tpu.operator.batch import (
        LookupRecentDaysBatchOp,
        LookupValueInTimeSeriesBatchOp,
        LookupVectorInTimeSeriesBatchOp,
    )

    day = 86400.0
    series = MTable({"ts": np.asarray([0.0, day, 2 * day, 3 * day]),
                     "val": np.asarray([1.0, 2.0, 3.0, 4.0])})
    vec_series = MTable(
        {"ts": np.asarray([0.0, day]),
         "vec": np.asarray(["1 0", "0 1"], object)},
        TableSchema(["ts", "vec"],
                    [AlinkTypes.DOUBLE, AlinkTypes.DENSE_VECTOR]))
    t = MTable(
        {"s": np.asarray([series], object),
         "sv": np.asarray([vec_series], object),
         "when": np.asarray([2.5 * day])},
        TableSchema(["s", "sv", "when"],
                    [AlinkTypes.MTABLE, AlinkTypes.MTABLE,
                     AlinkTypes.DOUBLE]))
    src = TableSourceBatchOp(t)
    v = LookupValueInTimeSeriesBatchOp(
        selectedCol="s", timeCol="when",
        outputCol="v").link_from(src).collect()
    assert v.col("v")[0] == 3.0  # latest value at or before t
    vv = LookupVectorInTimeSeriesBatchOp(
        selectedCol="sv", timeCol="when",
        outputCol="vec").link_from(src).collect()
    assert vv.col("vec")[0].data.tolist() == [0.0, 1.0]
    rd = LookupRecentDaysBatchOp(
        selectedCol="s", timeCol="when", numDays=2,
        outputCol="st").link_from(src).collect()
    stats = rd.col("st")[0].data
    assert stats[0] == 2.0  # count: days 2 and 3 fall in the window
    assert stats[1] == 5.0  # sum 2 + 3


def test_forecast_stream_twins():
    from alink_tpu.operator.stream import (
        ArimaStreamOp,
        AutoGarchStreamOp,
        HoltWintersStreamOp,
        TableSourceStreamOp,
    )

    y = _seasonal(72)
    src = TableSourceStreamOp(MTable({"v": y}), numChunks=2)
    out = HoltWintersStreamOp(valueCol="v", frequency=12,
                              predictNum=3).link_from(src).collect()
    assert out.num_rows == 2  # one forecast row per micro-batch window
    assert out.col("forecast")[0].data.shape == (3,)

"""Benchmark driver. Prints ONE JSON line:
{"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "extras": {...}}

Primary metric (north star, BASELINE.json): BERT-base fine-tune training
throughput in samples/sec/chip, seq len 128, batch 32, bf16 compute — this
framework's flagship path (flax TransformerEncoder + optax adamw, one jit).
vs_baseline compares against the commonly reported A100 BERT-base fine-tune
figure of ~210 samples/sec (seq128, fp16, bs32) — the driver-named target;
the reference itself publishes no numbers ("published": {}).

"extras" carries every other measurable BASELINE config:
- #1 kmeans_iris: Pipeline fit+transform wall-clock on an iris-shaped table
  (150x4, 3 clusters) + cluster quality.
- #2 softmax_mnist: SoftmaxTrainBatchOp (L-BFGS, one compiled program) on
  MNIST-shaped data (784 features, 10 classes) — samples/sec + accuracy.
- #3 resnet50_predict: ResNet-50 (defined in torch, ingested via
  torch.export -> StableHLO -> jit) batch inference rows/sec;
  resnet50_savedmodel is the metric-of-record TF SavedModel path
  (SavedModelBundle replacement), on-device rows/sec at bf16 + fp32.
- #5 torch_stream_predict: TorchModelPredictStreamOp rows/sec on a micro-
  batch stream.
- gbdt_train: histogram GBDT training throughput (riskiest perf item).
- bert_text_quality: REAL-TEXT holdout accuracy (the metric of record since
  r6): MLM pretrain on data/reviews_unlabeled.txt -> HF checkpoint ->
  fine-tune on the data/sst2_mini.csv train split -> holdout accuracy.
- bert_mfu: achieved TFLOPs/chip + MFU for the primary metric, plus the
  in-process gates: mfu vs the recorded floor (MFU_FLOOR), async-vs-sync
  feed perf_gate, and the steady-loop jit.trace delta (must be 0).
- serving: online serving tier drill — sustained concurrent clients against
  one loaded model (rows/s, batch-fill ratio, request p50/p90/p99, jit trace
  delta after warmup) plus a past-capacity load-shedding probe.
- coldstart: zero-cold-start gate — kmeans_iris in two fresh interpreters
  sharing one ALINK_COMPILE_CACHE_DIR; the second process must reach its
  first result on persist-hits, bit-identical, judged by benchstats
  (run standalone via ``python bench.py --only coldstart``).
- profiling: performance observatory drill — per-kernel XLA cost/roofline
  table, profiling off-vs-on overhead delta + bit-parity, benchstats perf
  gate smoke (same-config no-change; synthetic 20% slowdown flagged).
- train_scale: corpus-scale training drill — streaming-ingestion rows/s vs
  the in-memory feed (bit-parity + bounded-resident-rows gates), gradient-
  accumulation overhead at equal effective batch (micro vs fused parity),
  and the 2-process data-parallel pretrain drill (bit-identical to
  single-process accum_steps=2; scaling row informational on CPU meshes).
- aps: pod-scale sparse-embedding exchange — owner-routed pull/push rows/s
  on the sharded-skipgram pattern, per-device comm-bytes-per-step at M=1
  vs the full model axis (the regression-gated O(B·D) claim), and a
  perf_gate verdict of routed vs the legacy all-gather step.

``python bench.py --compare OLD.json NEW.json`` runs the variance-hardened
regression gate over two BENCH round files instead of benchmarking (exit
code 1 when a significant regression is flagged); see docs/bench_schema.md.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

A100_BERT_BASE_SAMPLES_PER_SEC = 210.0

PER_CHIP_BATCH = 32  # matches the baseline's per-device batch
SEQ = 128
WARMUP_STEPS = 3
TIMED_STEPS = 30
FEED_GATE_STEPS = 8   # steps per thunk in the async-vs-sync feed gate
# the recorded MFU floor (BENCH_r04): the in-process gate flags any round
# where the measured MFU lands below it, so an r04->r05-style drop fails
# loudly at bench time instead of landing silently in the round archive
MFU_FLOOR = 0.74


def bench_bert():
    import jax
    import optax

    from alink_tpu.dl.modules import BertConfig, TransformerEncoder
    from alink_tpu.dl.sharding import batch_sharding, param_shardings
    from alink_tpu.dl.train import make_train_step
    from alink_tpu.parallel.mesh import default_mesh

    n_chips = len(jax.devices())
    mesh = default_mesh()
    batch = PER_CHIP_BATCH * n_chips  # global batch scales with chips
    cfg = BertConfig.base(num_labels=2, dropout=0.0)  # bf16 compute by default
    model = TransformerEncoder(cfg)

    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (batch, SEQ)).astype(np.int32)
    amask = np.ones((batch, SEQ), np.int32)
    y = rng.randint(0, 2, batch).astype(np.int32)

    params = model.init(jax.random.PRNGKey(0), ids[:1], amask[:1])
    params = jax.device_put(params, param_shardings(params, mesh))
    tx = optax.adamw(2e-5, weight_decay=0.01)
    opt_state = tx.init(params["params"])

    def ce(logits, yy):
        return optax.softmax_cross_entropy_with_integer_labels(logits, yy).mean()

    train_step = make_train_step(model, tx, ce)

    ids = jax.device_put(ids, batch_sharding(mesh, 2))
    amask = jax.device_put(amask, batch_sharding(mesh, 2))
    y = jax.device_put(y, batch_sharding(mesh, 1))
    batch_args = {"input_ids": ids, "attention_mask": amask}

    def run(steps):
        nonlocal params, opt_state
        t0 = time.perf_counter()
        for _ in range(steps):
            params, opt_state, l = train_step(params, opt_state, batch_args, y)
        _ = float(l)  # force full materialization through the runtime
        return time.perf_counter() - t0

    from alink_tpu.common.benchstats import (measure_interleaved, perf_gate,
                                             trimmed_mean)
    from alink_tpu.common.metrics import metrics as _metrics

    run(WARMUP_STEPS)  # compile + cache warm
    # delta between two run lengths cancels dispatch/sync overhead.
    # Variance hardening (the r04->r05 lesson, docs/bert_regression_r05.md):
    # the two run lengths are measured INTERLEAVED hi,lo,hi,lo,... via
    # benchstats, so shared-container contention during the window charges
    # both lengths equally instead of corrupting the subtraction, and the
    # trimmed mean rejects interference outliers on each side
    eff_steps = TIMED_STEPS - TIMED_STEPS // 3
    tr0 = _metrics.counter("jit.trace")
    # repeats must be >= 5: trimmed(trim=0.2) drops int(n*0.2) per side, so
    # 4 samples would trim NOTHING and one contention spike would ride the
    # plain mean straight into the headline number
    samples = measure_interleaved(
        {"hi": lambda: run(TIMED_STEPS), "lo": lambda: run(TIMED_STEPS // 3)},
        repeats=5, warmup=1)
    # the steady-state loop must not retrace: any growth here means the hot
    # path lost shape stability (CI pins the same invariant on the real
    # train loop in tests/test_train_async.py)
    steady_trace_delta = _metrics.counter("jit.trace") - tr0
    dt = max(trimmed_mean(samples["hi"]) - trimmed_mean(samples["lo"]), 1e-9)

    samples_per_sec = batch * eff_steps / dt
    per_chip = samples_per_sec / n_chips

    # async device feed vs synchronous reference feed on the SAME compiled
    # step, fresh host batches every step (the train_model hot path): the
    # gate verdict proves the async pipeline never regresses step time, and
    # on a wire-bound setup shows the overlap win
    from alink_tpu.dl.train import _feed

    rng_f = np.random.RandomState(1)
    host_batches = [
        (rng_f.randint(0, cfg.vocab_size, (batch, SEQ)).astype(np.int32),
         np.ones((batch, SEQ), np.int32),
         rng_f.randint(0, 2, batch).astype(np.int32))
        for _ in range(FEED_GATE_STEPS)
    ]
    sh2, sh1 = batch_sharding(mesh, 2), batch_sharding(mesh, 1)

    def place(arrs):
        devs = [jax.device_put(a, s) for a, s in zip(arrs, (sh2, sh2, sh1))]
        jax.block_until_ready(devs)
        return devs

    def feed_thunk(mode):
        def thunk():
            nonlocal params, opt_state
            l = None
            for _s, devs in _feed(lambda s: list(host_batches[s]), place,
                                  FEED_GATE_STEPS, mode=mode):
                params, opt_state, l = train_step(
                    params, opt_state,
                    {"input_ids": devs[0], "attention_mask": devs[1]},
                    devs[2])
            jax.block_until_ready(l)
        return thunk

    feed_gate = perf_gate(feed_thunk("sync"), feed_thunk("async"),
                          repeats=5, warmup=1)

    # achieved model FLOPs + MFU so perf work has a target (VERDICT r3 #4).
    # Train FLOPs/token ~= 6*N_matmul + 12*L*S*H (fwd 2N + attn 4LSH, bwd 2x)
    H, L, S = cfg.hidden_size, cfg.num_layers, SEQ
    n_matmul = 12 * L * H * H + H * H  # per-layer qkv/out/mlp + pooler
    flops_per_sample = S * (6 * n_matmul + 12 * L * S * H)

    # cost_analysis-derived FLOPs for the SAME compiled step, so the MFU
    # denominator is measured by the compiler, not hand-maintained (the
    # analytic formula stays as the fallback when the backend reports
    # nothing, and for trajectory continuity with earlier rounds). Lowered
    # AFTER the timed window: tracing must not perturb the measurement.
    xla_flops_per_sample = None
    try:
        from alink_tpu.common.profiling import xla_cost_analysis

        lowered = train_step.lower(params, opt_state, batch_args, y)
        step_flops = xla_cost_analysis(lowered).get("flops")
        if step_flops:
            xla_flops_per_sample = step_flops / batch
    except Exception:
        pass

    # "mfu"/"achieved_tflops_per_chip" STAY on the analytic basis — the
    # r01..r05 trajectory stores that basis, and --compare intersects shared
    # keys, so switching the denominator would read as a phantom MFU delta.
    # The cost_analysis-derived figures ride alongside under *_xla keys.
    achieved_tflops = per_chip * flops_per_sample / 1e12
    achieved_xla = (per_chip * xla_flops_per_sample / 1e12
                    if xla_flops_per_sample else None)
    # one peaks table for the whole repo (profiling.device_peaks, env
    # overrides included); CPU dev containers keep the historical mfu=None
    from alink_tpu.common.profiling import device_peaks

    peaks = device_peaks()
    kind = peaks["device_kind"]
    peak = (peaks["peak_flops_per_s"] / 1e12
            if peaks["peak_flops_per_s"] and "cpu" not in kind.lower()
            else None)
    mfu = {"device_kind": kind,
           "model_tflops_per_sample": round(flops_per_sample / 1e12, 5),
           "xla_tflops_per_sample":
               round(xla_flops_per_sample / 1e12, 5)
               if xla_flops_per_sample else None,
           "achieved_tflops_per_chip": round(achieved_tflops, 1),
           "mfu": round(achieved_tflops / peak, 3) if peak else None,
           "achieved_tflops_per_chip_xla":
               round(achieved_xla, 1) if achieved_xla else None,
           "mfu_xla": round(achieved_xla / peak, 3)
           if peak and achieved_xla else None,
           "peak_tflops_assumed": peak}
    mval = mfu["mfu"]
    mfu["mfu_gate"] = {
        "floor": MFU_FLOOR,
        # None = no device peak on record (CPU dev container): nothing to
        # gate; on an accelerator a sub-floor reading is a loud failure
        "ok": bool(mval is None or mval >= MFU_FLOOR),
    }
    mfu["steady_trace_delta"] = int(steady_trace_delta)
    mfu["feed_gate"] = dict(feed_gate,
                            async_not_slower=feed_gate["verdict"] != "regression")
    return per_chip, mfu


def bench_kmeans_iris():
    """#1: the REAL iris dataset (data/iris.csv, Fisher 1936 via sklearn)
    through the Pipeline API — wall-clock + cluster purity vs true species
    (the README quick-start workload)."""
    import os

    from alink_tpu.operator.batch.base import CsvSourceBatchOp
    from alink_tpu.pipeline import KMeans, Pipeline

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "data", "iris.csv")
    src = CsvSourceBatchOp(
        filePath=path,
        schemaStr="sl double, sw double, pl double, pw double, species string")
    def fit_once():
        t0 = time.perf_counter()
        pipe = Pipeline(KMeans(
            k=3, maxIter=50, featureCols=["sl", "sw", "pl", "pw"],
            predictionCol="pred"))
        model = pipe.fit(src)
        out = model.transform(src).collect()
        return time.perf_counter() - t0, out

    wall, out = fit_once()          # includes compile (or cache load)
    wall_warm, _ = fit_once()       # compiled-program wall-clock
    labels = np.asarray(out.col("pred"))
    species = np.asarray(out.col("species"))
    purity = sum(
        np.unique(labels[species == s], return_counts=True)[1].max()
        for s in np.unique(species))
    return {"wall_clock_s": round(wall, 3),
            "wall_clock_warm_s": round(wall_warm, 3),
            "cluster_purity": round(purity / len(labels), 4)}


def bench_softmax_mnist():
    """#2: softmax via the distributed L-BFGS path. Throughput measures the
    MNIST-shaped workload (20k x 784, synthetic); accuracy is measured on
    the REAL handwritten-digits dataset (data/digits.csv, 1797 x 64,
    sklearn's UCI digits — the checked-in MNIST stand-in), train/test split
    so the number carries signal."""
    from alink_tpu.operator.batch import (SoftmaxPredictBatchOp,
                                          SoftmaxTrainBatchOp)
    from alink_tpu.common.mtable import MTable
    from alink_tpu.operator.batch.base import (CsvSourceBatchOp,
                                               TableSourceBatchOp)

    rng = np.random.default_rng(1)
    n, d, k = 20000, 784, 10
    W_true = rng.normal(size=(d, k)).astype(np.float32)
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = (X @ W_true + 0.5 * rng.normal(size=(n, k))).argmax(1)
    cols = {f"p{i}": X[:, i] for i in range(d)}
    cols["label"] = y.astype(np.int64)
    src = TableSourceBatchOp(MTable(cols))
    feature_cols = [f"p{i}" for i in range(d)]

    def run_once():
        t0 = time.perf_counter()
        train = SoftmaxTrainBatchOp(featureCols=feature_cols,
                                    labelCol="label", maxIter=30)
        model = train.link_from(src)
        SoftmaxPredictBatchOp().link_from(model, src).collect()
        return time.perf_counter() - t0

    # cold includes compile / persistent-cache load; warm is the compiled
    # steady state (min of 2 rejects tunnel-contention spikes — the r3
    # "regression" was an unsplit cold number measured under midday load).
    # Warm runs hit the device staging cache (common/staging.py): the 62MB
    # feature block is pushed once (as bf16 wire) and reused across jobs.
    from alink_tpu.common.staging import staging_cache_stats

    s0 = staging_cache_stats()
    wall_cold = run_once()
    s1 = staging_cache_stats()
    wall = min(run_once(), run_once())
    s2 = staging_cache_stats()
    staging = {
        "cold_wire_MB": round((s1["wire_bytes_sent"] - s0["wire_bytes_sent"]) / 1e6, 1),
        "warm_wire_MB": round((s2["wire_bytes_sent"] - s1["wire_bytes_sent"]) / 2e6, 1),
        "warm_cache_hits": s2["hits"] - s1["hits"],
        "bf16_wire_MB_saved": round((s2["wire_bytes_saved"] - s0["wire_bytes_saved"]) / 1e6, 1),
    }
    effective_samples = n * 30  # samples touched per L-BFGS data pass

    # real-data accuracy: UCI digits with an 80/20 split
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "data", "digits.csv")
    dcols = [f"p{i}" for i in range(64)]
    schema = ", ".join(f"{c} double" for c in dcols) + ", label long"
    digits = CsvSourceBatchOp(filePath=path, schemaStr=schema).collect()
    split = int(digits.num_rows * 0.8)
    shuffled = digits.shuffle(seed=0)
    tr, te = shuffled.split_at(split)
    m2 = SoftmaxTrainBatchOp(
        featureCols=dcols, labelCol="label", maxIter=60,
    ).link_from(TableSourceBatchOp(tr))
    pred = SoftmaxPredictBatchOp().link_from(
        m2, TableSourceBatchOp(te)).collect()
    acc = float((np.asarray(pred.col("pred"))
                 == np.asarray(te.col("label"))).mean())
    return {"samples_per_sec": round(effective_samples / wall, 1),
            "samples_per_sec_cold": round(effective_samples / wall_cold, 1),
            "accuracy_digits_holdout": round(acc, 4),
            "wall_clock_s": round(wall, 3),
            "wall_clock_cold_s": round(wall_cold, 3),
            "staging": staging}


def _resnet50_torch():
    import torch
    import torch.nn as nn

    class Bottleneck(nn.Module):
        def __init__(self, cin, planes, stride=1):
            super().__init__()
            cout = planes * 4
            self.conv1 = nn.Conv2d(cin, planes, 1, bias=False)
            self.bn1 = nn.BatchNorm2d(planes)
            self.conv2 = nn.Conv2d(planes, planes, 3, stride=stride,
                                   padding=1, bias=False)
            self.bn2 = nn.BatchNorm2d(planes)
            self.conv3 = nn.Conv2d(planes, cout, 1, bias=False)
            self.bn3 = nn.BatchNorm2d(cout)
            self.relu = nn.ReLU()
            self.down = None
            if stride != 1 or cin != cout:
                self.down = nn.Sequential(
                    nn.Conv2d(cin, cout, 1, stride=stride, bias=False),
                    nn.BatchNorm2d(cout))

        def forward(self, x):
            identity = self.down(x) if self.down is not None else x
            out = self.relu(self.bn1(self.conv1(x)))
            out = self.relu(self.bn2(self.conv2(out)))
            out = self.bn3(self.conv3(out))
            return self.relu(out + identity)

    class ResNet50(nn.Module):
        def __init__(self, num_classes=1000):
            super().__init__()
            self.stem = nn.Sequential(
                nn.Conv2d(3, 64, 7, stride=2, padding=3, bias=False),
                nn.BatchNorm2d(64), nn.ReLU(),
                nn.MaxPool2d(3, stride=2, padding=1))
            layers = []
            cin = 64
            for planes, blocks, stride in ((64, 3, 1), (128, 4, 2),
                                           (256, 6, 2), (512, 3, 2)):
                for b in range(blocks):
                    layers.append(Bottleneck(cin, planes,
                                             stride if b == 0 else 1))
                    cin = planes * 4
            self.layers = nn.Sequential(*layers)
            self.head = nn.Sequential(nn.AdaptiveAvgPool2d(1), nn.Flatten(),
                                      nn.Linear(2048, num_classes))

        def forward(self, x):
            return self.head(self.layers(self.stem(x)))

    torch.manual_seed(0)
    return ResNet50().eval()


def bench_resnet50(batch=256, steps=3):
    """#3: ResNet-50 batch inference rows/sec through the torch.export ->
    StableHLO ingest path (the SavedModelBundle analog on TPU). The e2e path
    models the real serving pipeline: decoded images are uint8 NHWC on the
    host (37.5KB/row on the wire — 4x less than fp32 NCHW), normalization +
    layout transpose + the model are fused into ONE XLA program, and batches
    dispatch ahead so transfer overlaps compute. Reports:
    Serving runs the bfloat16 inference policy (precision="bfloat16" on
    the ingest ops: MXU-native matmuls/convs, ~2x the fp32 on-device rate;
    fp32-agreement is covered by tests/test_ingest.py on an MLP — random-
    weight ResNet top-1 agreed 64/64 in manual runs, not a CI gate).
    - rows_per_sec: host uint8 in -> host logits out (includes transfer)
    - rows_per_sec_on_device: input pre-staged, the same fused
      normalize+model program, bf16 policy
    - rows_per_sec_on_device_fp32: ditto at fp32 (numerics-parity path)
    - tunnel_MB_per_s + wire_floor_rows_per_sec: measured device_put
      bandwidth and the throughput ceiling it implies for this wire format
      (under axon the tunnel, not the chip, is the binding constraint)."""
    import jax
    import jax.numpy as jnp
    import torch

    from alink_tpu.onnx import load_torch_fn

    model = _resnet50_torch()
    x = torch.randn(batch, 3, 224, 224)
    ep = torch.export.export(model.eval(), (x,))  # export once, trace twice
    # bf16 inference policy: MXU-native matmuls/convs, half the HBM traffic
    fn, _ = load_torch_fn(ep, dtype="bfloat16")
    fn32, _ = load_torch_fn(ep)

    mean = np.array([0.485, 0.456, 0.406], np.float32) * 255.0
    std = np.array([0.229, 0.224, 0.225], np.float32) * 255.0

    def make_serve(f):
        @jax.jit
        def serve(u8):  # uint8 NHWC in; normalize/transpose fused on device
            xf = (u8.astype(jnp.float32) - mean) / std
            return f(xf.transpose(0, 3, 1, 2))[0]

        return serve

    serve, serve32 = make_serve(fn), make_serve(fn32)

    rng = np.random.RandomState(0)
    bufs = [rng.randint(0, 256, (batch, 224, 224, 3), np.uint8)
            for _ in range(steps)]
    np.asarray(serve(bufs[0]))  # compile (fetch: block_until_ready is not a
    # reliable sync point through the axon tunnel)

    # measured wire bandwidth with a forced round trip (a dependent fetch),
    # since device_put+block_until_ready can return before the wire moves;
    # a tiny warmup probe first so the gather compile isn't in the window
    _ = float(jax.device_put(rng.randint(0, 256, (1024,), np.uint8))[0])
    probe = rng.randint(0, 256, (19_200_000,), np.uint8)
    t0 = time.perf_counter()
    _ = float(jax.device_put(probe)[0])
    mbps = 19.2 / (time.perf_counter() - t0)
    row_bytes = 224 * 224 * 3
    wire_floor = mbps * 1e6 / row_bytes

    # end-to-end through the double-buffered streamer (common/streaming.py):
    # each batch ships as 4 parallel row-chunk transfers reassembled on
    # device (the tunnel is per-stream limited, so aggregate wire bandwidth
    # scales with stream count), device_put of batch k+1 overlaps compute on
    # batch k, and logits are trimmed + concatenated ON DEVICE and fetched
    # with one host transfer
    import jax.numpy as jnp

    from alink_tpu.common.streaming import stream_map

    stream_phases = {}
    t0 = time.perf_counter()
    refs = [r for _, r in stream_map(
        serve, ((i, [b]) for i, b in enumerate(bufs)),
        depth=max(2, steps - 1), split=4, phases=stream_phases)]
    logits = np.asarray(jnp.concatenate(refs, axis=0))
    dt = time.perf_counter() - t0
    assert logits.shape == (batch * steps, 1000)

    # device-resident variants: stage once, time the SAME fused serve
    # program (bf16 policy + the fp32 numerics-parity path)
    def time_dev(f, reps=steps):
        xd = jax.device_put(bufs[0])
        np.asarray(f(xd)[:1, :1])
        t1 = time.perf_counter()
        for _ in range(reps):
            out_d = f(xd)
        _ = np.asarray(out_d[:1, :1])  # dependent fetch = real sync
        return batch * reps / (time.perf_counter() - t1)

    return {"rows_per_sec": round(batch * steps / dt, 1),
            "rows_per_sec_on_device": round(time_dev(serve), 1),
            "rows_per_sec_on_device_fp32": round(time_dev(serve32), 1),
            "tunnel_MB_per_s": round(mbps, 1),
            "wire_floor_rows_per_sec": round(wire_floor, 1),
            "stream": {"wall_s": round(dt, 3),
                       "transfer_s": round(
                           stream_phases.get("transfer_s", 0.0), 3),
                       "compute_s": round(
                           stream_phases.get("compute_s", 0.0), 3),
                       "in_flight": max(2, steps - 1), "split": 4},
            "batch": batch}


def bench_resnet50_savedmodel(batch=128, steps=8):
    """#3's metric-of-record path verbatim: a TF SavedModel ResNet-50
    compiled to ONE XLA program (the SavedModelBundle replacement,
    reference: predictor-tf TFPredictorServiceImpl.java:139). On-device
    bf16 rows/sec (the serving policy; the fp32 figure lives in
    resnet50_predict, numerics vs TF are pinned by tests/test_tfsaved.py).
    Keras build + freeze + compile dominate the wall — one precision keeps
    the bench inside the driver's window."""
    import tempfile

    import jax
    import tensorflow as tf

    from alink_tpu.onnx.tfsaved import load_saved_model_fn

    model = tf.keras.applications.ResNet50(weights=None)
    d = os.path.join(tempfile.mkdtemp(), "rn50")
    tf.saved_model.save(model, d)
    x = np.random.RandomState(0).rand(batch, 224, 224, 3).astype(np.float32)

    def time_fn(jfn, reps=steps):
        xd = jax.device_put(x)
        np.asarray(jfn(xd)[0][:1, :1])  # compile + real sync
        t0 = time.perf_counter()
        for _ in range(reps):
            out = jfn(xd)
        _ = np.asarray(out[0][:1, :1])
        return batch * reps / (time.perf_counter() - t0)

    jfn16, _, _ = load_saved_model_fn(d, dtype="bfloat16")
    return {"rows_per_sec_on_device": round(time_fn(jfn16), 1),
            "batch": batch}


def bench_torch_stream(rows=16384):
    """#5: Torch model predict through the stream op, rows/sec. Micro-batches
    are pipelined (dispatch-ahead in MapStreamOp, one device round trip per
    chunk each way) and sized so tunnel round-trip latency, not chunk count,
    sets the floor. Cold run includes the per-shape XLA compile; warm is the
    steady-state serving number."""
    import tempfile

    import torch
    import torch.nn as nn

    from alink_tpu.common.mtable import MTable
    from alink_tpu.operator.stream.base import TableSourceStreamOp
    from alink_tpu.operator.stream import TorchModelPredictStreamOp

    torch.manual_seed(0)
    model = nn.Sequential(nn.Linear(16, 64), nn.ReLU(),
                          nn.Linear(64, 1)).eval()
    ep = torch.export.export(model, (torch.randn(4, 16),))
    path = os.path.join(tempfile.mkdtemp(), "m.pt2")
    torch.export.save(ep, path)

    X = np.random.RandomState(0).randn(rows, 16).astype(np.float64)
    cols = {f"f{i}": X[:, i] for i in range(16)}
    def run():
        src = TableSourceStreamOp(MTable(cols), chunkSize=4096)
        op = TorchModelPredictStreamOp(
            modelPath=path, selectedCols=[f"f{i}" for i in range(16)],
            outputCols=["score"], predictBatchSize=4096).link_from(src)
        t0 = time.perf_counter()
        out = op.collect()
        return time.perf_counter() - t0, out

    cold, out = run()
    warm, out = run()
    assert out.num_rows == rows
    return {"rows_per_sec": round(rows / warm, 1),
            "rows_per_sec_cold": round(rows / cold, 1)}


def bench_gbdt(n=50000, d=20):
    """GBDT histogram training throughput (SURVEY's riskiest perf item).
    The whole boosting run is ONE device program; histograms are one-hot
    matmuls on the MXU. Reports the warm run (compile amortizes across jobs
    via the persistent XLA cache) plus the cold wall and per-phase split."""
    from alink_tpu.tree.grow import train_gbdt

    rng = np.random.default_rng(2)
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] ** 2 > 0.3).astype(np.float32)
    t0 = time.perf_counter()
    train_gbdt(X, y, task="binary", num_trees=20, depth=6, num_bins=64)
    cold = time.perf_counter() - t0
    phases = {}
    t0 = time.perf_counter()
    ens = train_gbdt(X, y, task="binary", num_trees=20, depth=6,
                     num_bins=64, phase_metrics=phases)
    dt = time.perf_counter() - t0
    acc = float(((ens.raw_predict(X)[:, 0] > 0) == (y > 0)).mean())
    return {"samples_per_sec": round(n * 20 / dt, 1),
            "trees": 20, "depth": 6, "wall_clock_s": round(dt, 2),
            "cold_wall_clock_s": round(cold, 2),
            "train_accuracy": round(acc, 4), "phases": phases}


def bench_bert_quality():
    """Quality signal for the BERT path — the REAL-TEXT metric of record
    (ROADMAP open item 4; replaces the synthetic token-identity task whose
    0.88 sat pinned since r3). Runs the full in-framework story end-to-end
    on the shipped corpora: MLM-pretrain on ``data/reviews_unlabeled.txt``,
    export the HF-layout checkpoint, fine-tune through
    ``checkpointFilePath`` on the ``data/sst2_mini.csv`` train split, and
    report holdout accuracy on the held-out rows (``dl.data.sst2_split`` —
    the same split the tests pin). Random init scores ~0.5; the pretrained
    encoder must clearly beat it for the round to carry learning evidence.
    Reported under a new leaf (``real_holdout_accuracy``) so ``--compare``
    never diffs the real-text series against the old synthetic one."""
    import shutil
    import tempfile

    from alink_tpu.common.mtable import MTable
    from alink_tpu.dl.data import load_reviews, sst2_split
    from alink_tpu.dl.pretrain import pretrain_and_save
    from alink_tpu.operator.batch.base import TableSourceBatchOp
    from alink_tpu.operator.batch.dl import (
        BertTextClassifierPredictBatchOp, BertTextClassifierTrainBatchOp)

    t0 = time.perf_counter()
    ckpt_dir = tempfile.mkdtemp(prefix="alink_bench_bert_")
    try:
        pre = pretrain_and_save(
            load_reviews(), ckpt_dir, vocab_size=2000, hidden_size=96,
            num_layers=2, num_heads=4, intermediate_size=192, max_len=32,
            epochs=5, batch_size=64, learning_rate=3e-4, seed=0)
        t_pre = time.perf_counter()

        tr_t, tr_y, ho_t, ho_y = sst2_split(seed=0)
        m = BertTextClassifierTrainBatchOp(
            textCol="text", labelCol="label", checkpointFilePath=ckpt_dir,
            maxSeqLength=32, numEpochs=14, batchSize=32, learningRate=5e-4,
            randomSeed=0, poolingStrategy="mean",  # NSP-less checkpoint
        ).link_from(TableSourceBatchOp(MTable({"text": tr_t, "label": tr_y})))
        pred = BertTextClassifierPredictBatchOp(predictionCol="p").link_from(
            m, TableSourceBatchOp(MTable({"text": ho_t, "label": ho_y}))
        ).collect()
        acc = float((np.asarray(pred.col("p")) == ho_y).mean())
    finally:
        shutil.rmtree(ckpt_dir, ignore_errors=True)
    return {
        "real_holdout_accuracy": round(acc, 4),
        "task": "reviews_unlabeled MLM pretrain -> sst2_mini finetune",
        "train_rows": len(tr_t), "holdout_rows": len(ho_t),
        "pretrain": {"mlm_initial_loss": pre["initial_loss"],
                     "mlm_final_loss": pre["final_loss"],
                     "vocab_size": pre["vocab_size"],
                     "wall_clock_s": round(t_pre - t0, 2)},
        "wall_clock_s": round(time.perf_counter() - t0, 2),
    }


def bench_train_scale():
    """Corpus-scale training drill (ROADMAP item 3): streaming ingestion
    rows/s vs the in-memory feed (bit-parity gated, peak resident rows
    bounded by the stream buffer), gradient-accumulation overhead at equal
    effective batch (micro-step schedule vs the fused large-batch
    reference, bit-parity gated), and a 2-process data-parallel pretrain
    drill over a real localhost jax.distributed cluster — bit-identical to
    single-process ``accum_steps=2`` at equal global batch, with a scaling
    row (rows/s at P=1 vs P=2). On a CPU dev container the 2-process wall
    reads cluster-formation + gloo overhead with none of the
    multi-host-HBM benefit, so the scaling row is informational there
    (``wall_gate_applies`` false, the PR 12 ``huge`` convention)."""
    import hashlib
    import socket
    import subprocess
    import sys as _sys
    import tempfile
    import textwrap

    import jax

    from alink_tpu.dl.data import CorpusStream, load_reviews
    from alink_tpu.dl.pretrain import pretrain_mlm
    from alink_tpu.dl.tokenizer import Tokenizer

    def digest(params):
        leaves = jax.tree_util.tree_leaves(params)
        return hashlib.sha256(
            b"".join(np.asarray(x).tobytes() for x in leaves)).hexdigest()

    import shutil

    texts = load_reviews()
    n = len(texts)
    workdir = tempfile.mkdtemp(prefix="alink_train_scale_")
    corpus = os.path.join(workdir, "corpus.txt")
    with open(corpus, "w", encoding="utf-8") as f:
        f.write("\n".join(texts) + "\n")
    tok = Tokenizer.build(texts, vocab_size=800)
    kw = dict(hidden_size=32, num_layers=1, num_heads=2,
              intermediate_size=64, max_len=24, epochs=1, batch_size=64,
              seed=0, tokenizer=tok)
    block, buffer = 256, 512  # buffer << corpus (4.4k rows)

    # warm the MLM micro/apply programs once so neither timed run pays the
    # XLA compile (the ingestion comparison measures the FEED, not tracing)
    pretrain_mlm(texts[:256], block_rows=block, **kw)

    # -- streaming vs in-memory ingestion ---------------------------------
    cs = CorpusStream(corpus, block_rows=block, buffer_rows=buffer)
    t0 = time.perf_counter()
    _, p_stream, _, _ = pretrain_mlm(cs, **kw)
    stream_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    _, p_mem, _, _ = pretrain_mlm(texts, block_rows=block, **kw)
    mem_s = time.perf_counter() - t0
    stream_parity = digest(p_stream) == digest(p_mem)
    resident_ok = cs.max_resident_rows <= cs.buffer_rows

    # -- accumulation at equal effective batch ----------------------------
    t0 = time.perf_counter()
    _, p_a1, _, _ = pretrain_mlm(texts, block_rows=block, accum_steps=1,
                                 **kw)
    accum1_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    _, p_a4, _, _ = pretrain_mlm(texts, block_rows=block, accum_steps=4,
                                 **kw)
    accum4_s = time.perf_counter() - t0
    # micro-vs-fused bit-parity on the fine-tune loop (the CI-pinned
    # contract, re-checked here on the bench config)
    from alink_tpu.dl.modules import KerasSequential
    from alink_tpu.dl.train import TrainConfig, train_model

    rngb = np.random.default_rng(0)
    Xb = rngb.normal(size=(256, 8)).astype(np.float32)
    yb = (Xb[:, 0] > 0).astype(np.int32)

    def _job(mode):
        return train_model(
            KerasSequential(("Dense(10, activation=relu)",), out_dim=2),
            {"x": Xb}, yb,
            TrainConfig(num_epochs=1, batch_size=64, seed=1, accum_steps=4,
                        accum_mode=mode), seq_axis=None)[0]

    accum_parity = digest(_job("micro")) == digest(_job("fused"))

    # -- 2-process data-parallel drill ------------------------------------
    worker = textwrap.dedent("""
        import os, sys, json, hashlib, time
        os.environ["JAX_PLATFORMS"] = os.environ.get("ALINK_BENCH_PLATFORM", "cpu")
        sys.path.insert(0, __REPO__)
        os.environ["COORDINATOR_ADDRESS"] = __COORD__
        os.environ["NUM_PROCESSES"] = "2"
        os.environ["PROCESS_ID"] = sys.argv[1]
        import numpy as np
        import jax
        from alink_tpu.dl.data import CorpusStream
        from alink_tpu.dl.pretrain import pretrain_mlm
        from alink_tpu.dl.tokenizer import Tokenizer
        texts = [t for t in open(__CORPUS__, encoding="utf-8")
                     .read().splitlines() if t.strip()]
        tok = Tokenizer.build(texts, vocab_size=800)
        cs = CorpusStream(__CORPUS__, block_rows=256, buffer_rows=512)
        t0 = time.perf_counter()
        _, params, _, _ = pretrain_mlm(
            cs, hidden_size=32, num_layers=1, num_heads=2,
            intermediate_size=64, max_len=24, epochs=1, batch_size=64,
            seed=0, tokenizer=tok)
        wall = time.perf_counter() - t0
        leaves = jax.tree_util.tree_leaves(params)
        dig = hashlib.sha256(
            b"".join(np.asarray(x).tobytes() for x in leaves)).hexdigest()
        print(json.dumps({"pid": int(sys.argv[1]), "digest": dig,
                          "train_wall_s": wall, "rows": len(texts)}))
    """)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    script = os.path.join(workdir, "worker.py")
    repo = os.path.dirname(os.path.abspath(__file__))
    with open(script, "w") as f:
        f.write(worker.replace("__REPO__", repr(repo))
                .replace("__COORD__", repr(f"127.0.0.1:{port}"))
                .replace("__CORPUS__", repr(corpus)))
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    t0 = time.perf_counter()
    procs = [subprocess.Popen([_sys.executable, script, str(pid)],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.PIPE, env=env, text=True)
             for pid in (0, 1)]
    try:
        outs = [p.communicate(timeout=300) for p in procs]
    except subprocess.TimeoutExpired:
        for p in procs:  # a hung worker must not orphan its peer
            p.kill()
        outs = [p.communicate() for p in procs]
    two_proc_wall = time.perf_counter() - t0
    two_proc = {"error": None}
    if any(p.returncode for p in procs):
        two_proc = {"error": (outs[0][1] or outs[1][1])[-300:]}
        dp_parity = False
        train_wall_2p = None
    else:
        payloads = [json.loads(o.strip().splitlines()[-1])
                    for o, _ in outs]
        # reference: single process, accum_steps = P at equal global batch
        t0 = time.perf_counter()
        _, p_ref, _, _ = pretrain_mlm(
            CorpusStream(corpus, block_rows=block, buffer_rows=buffer),
            accum_steps=2, **kw)
        ref_s = time.perf_counter() - t0
        dp_parity = (payloads[0]["digest"] == payloads[1]["digest"]
                     == digest(p_ref))
        train_wall_2p = max(p["train_wall_s"] for p in payloads)
        two_proc = {
            "train_wall_s": round(train_wall_2p, 3),
            "spawn_to_done_s": round(two_proc_wall, 3),
            "rows_per_s": round(n / train_wall_2p, 1),
            "single_proc_accum2_wall_s": round(ref_s, 3),
            "single_proc_accum2_rows_per_s": round(n / ref_s, 1),
        }
    # a CPU mesh pays gloo + double jax startup for zero HBM benefit: the
    # scaling row is informational there (same convention as `huge`)
    kind = jax.devices()[0].device_kind.lower()
    wall_gate_applies = not ("cpu" in kind or "host" in kind)

    gate = {
        "streaming_bit_parity": bool(stream_parity),
        "resident_rows_bounded": bool(resident_ok),
        "accum_bit_parity": bool(accum_parity),
        "two_proc_bit_parity": bool(dp_parity),
        "wall_gate_applies": wall_gate_applies,
    }
    gate["ok"] = all(v for k, v in gate.items()
                     if k not in ("wall_gate_applies",))
    shutil.rmtree(workdir, ignore_errors=True)
    return {
        "corpus_rows": n,
        "buffer_rows": buffer,
        "max_resident_rows": cs.max_resident_rows,
        "streaming_rows_per_s": round(n / stream_s, 1),
        "in_memory_rows_per_s": round(n / mem_s, 1),
        "streaming_wall_s": round(stream_s, 3),
        "in_memory_wall_s": round(mem_s, 3),
        "accum1_wall_s": round(accum1_s, 3),
        "accum4_wall_s": round(accum4_s, 3),
        "accum_overhead_pct": round((accum4_s / max(accum1_s, 1e-9) - 1)
                                    * 100, 1),
        "two_proc": two_proc,
        "gate": gate,
    }


def bench_executor(rows=2_000_000):
    """Pipelined DAG executor (common/executor.py): two independent branches
    off one shared source run concurrently on the DAG pool, and a 3-op
    row-wise mapper chain fuses into a single jitted unit. Reports the
    engine's own per-node trace (the same records BENCH readers should use
    to diagnose scheduling regressions): node wall times, the transfer/
    compute phase split where nodes report one, fused-chain count, and the
    concurrency win vs the old serial walk (node_wall_sum ≈ what depth-first
    evaluation would have cost)."""
    from alink_tpu.common.metrics import executor_trace, metrics
    from alink_tpu.common.mtable import AlinkTypes, MTable
    from alink_tpu.mapper.base import BlockKernelMapper
    from alink_tpu.operator.batch import TableSourceBatchOp
    from alink_tpu.operator.batch.utils import MapBatchOp

    def affine_op(col, out, a, b):
        class _M(BlockKernelMapper):
            def kernel(self, schema):
                def fn(X):
                    return X * a + b

                return ([col], [out], [AlinkTypes.DOUBLE], fn)

        class _Op(MapBatchOp):
            mapper_cls = _M

        return _Op()

    rng = np.random.RandomState(0)
    src = TableSourceBatchOp(
        MTable({"x": rng.rand(rows), "y": rng.rand(rows)}))

    def branch(col):
        def work(t):
            v = np.asarray(t.col(col))
            for _ in range(4):  # real host work, ~O(0.5s) per branch
                v = np.sort(v)[::-1].copy()
            return MTable({col: v})

        return src.apply_func(work, out_schema=f"{col} double")

    chain = affine_op("x", "x1", 2.0, 1.0).link_from(src)
    chain = affine_op("x1", "x2", 0.5, -3.0).link_from(chain)
    chain = affine_op("x2", "x3", 4.0, 0.25).link_from(chain)

    n0 = len(executor_trace())
    sink: dict = {}
    branch("x").lazy_collect(lambda t: sink.setdefault("a", t.num_rows))
    branch("y").lazy_collect(lambda t: sink.setdefault("b", t.num_rows))
    chain.lazy_collect(lambda t: sink.setdefault("c", t.num_rows))
    t0 = time.perf_counter()
    src.execute()
    wall = time.perf_counter() - t0
    assert sink == {"a": rows, "b": rows, "c": rows}

    trace = executor_trace()[n0:]
    node_wall = sum(r.get("wall_s", 0.0) for r in trace)
    run = metrics.last("executor.run") or {}
    return {
        "wall_s": round(wall, 3),
        "node_wall_sum_s": round(node_wall, 3),
        "speedup_vs_serial": round(node_wall / wall, 2) if wall > 0 else None,
        "nodes": run.get("nodes"),
        "scheduled_units": run.get("units"),
        "fused_chains": run.get("fused_chains"),
        "trace": sorted(trace, key=lambda r: -r.get("wall_s", 0.0))[:6],
    }


def bench_resilience(rows=20_000):
    """Fault-tolerant runtime (common/resilience.py, common/faults.py):
    run a multi-branch DAG under a seeded 30% transient unit-fault rate and
    a Kafka memory-broker round trip under 2 injected transient IO faults,
    assert both produce output identical to the fault-free run, and report
    the resilience counters (retries absorbed, defusions, dead-letter
    volume) — the same readout long-running jobs should watch."""
    from alink_tpu.common import faults
    from alink_tpu.common.mtable import MTable
    from alink_tpu.common.resilience import resilience_summary
    from alink_tpu.io.kafka import MemoryKafkaBroker
    from alink_tpu.operator.batch import TableSourceBatchOp
    from alink_tpu.operator.stream import (KafkaSinkStreamOp,
                                           KafkaSourceStreamOp,
                                           TableSourceStreamOp)

    rng = np.random.RandomState(0)
    t = MTable({"x": rng.rand(rows), "y": rng.rand(rows)})

    def run_dag_job():
        src = TableSourceBatchOp(t)
        a = src.apply_func(
            lambda m: MTable({"x": np.sort(np.asarray(m.col("x")))}),
            out_schema="x double")
        b = src.apply_func(
            lambda m: MTable({"y": np.asarray(m.col("y")) * 2.0}),
            out_schema="y double")
        got = {}
        a.lazy_collect(lambda m: got.setdefault("a", np.asarray(m.col("x"))))
        b.lazy_collect(lambda m: got.setdefault("b", np.asarray(m.col("y"))))
        src.execute()
        return got

    def run_kafka_job(tag):
        rows_in = MTable.from_rows(
            [(i, float(i) * 0.5) for i in range(512)], "k long, v double")
        MemoryKafkaBroker.named(f"bench-res-{tag}")  # fresh broker
        sink = KafkaSinkStreamOp(
            bootstrapServers=f"memory://bench-res-{tag}", topic="t",
        ).link_from(TableSourceStreamOp(rows_in, chunkSize=128))
        for _ in sink._stream():
            pass
        out = []
        src = KafkaSourceStreamOp(
            bootstrapServers=f"memory://bench-res-{tag}", topic="t",
            schemaStr="k long, v double", maxMessages=512,
            idleTimeoutMs=200)
        for chunk in src._stream():
            out.extend(chunk.rows())
        return out

    faults.clear()
    clean_dag = run_dag_job()
    clean_kafka = run_kafka_job("clean")
    t0 = time.perf_counter()
    # widen the attempt budget under the 30% rate so the drill never
    # exhausts retries by seed luck (0.3^8 per unit)
    prev_attempts = os.environ.get("ALINK_RETRY_MAX_ATTEMPTS")
    os.environ["ALINK_RETRY_MAX_ATTEMPTS"] = "8"
    faults.install(faults.FaultSpec.parse(
        "unit:rate=0.3,kinds=transient;io:count=2", seed=7))
    try:
        faulty_dag = run_dag_job()
        faulty_kafka = run_kafka_job("faulty")
    finally:
        faults.clear()
        if prev_attempts is None:
            os.environ.pop("ALINK_RETRY_MAX_ATTEMPTS", None)
        else:
            os.environ["ALINK_RETRY_MAX_ATTEMPTS"] = prev_attempts
    wall = time.perf_counter() - t0
    dag_parity = all(
        np.array_equal(clean_dag[k], faulty_dag[k]) for k in ("a", "b"))
    return {
        "dag_parity_under_30pct_unit_faults": dag_parity,
        "kafka_parity_under_io_faults": clean_kafka == faulty_kafka,
        "faulted_wall_s": round(wall, 3),
        "counters": resilience_summary(),
    }


def bench_recovery(rows=50_000):
    """Exactly-once recovery runtime (common/recovery.py): a stateful
    windowed pipeline under epoch snapshotting — report the checkpoint tax
    (per-epoch snapshot/commit overhead vs. the raw drain), then kill the
    job mid-stream with an injected crash and report restore latency,
    chunks replayed, and bit-parity with the fault-free run. These numbers
    track the cost of the exactly-once tier across PRs."""
    import tempfile

    from alink_tpu.common import faults
    from alink_tpu.common.metrics import metrics
    from alink_tpu.common.mtable import MTable
    from alink_tpu.common.recovery import (RecoverableStreamJob,
                                           recovery_summary,
                                           run_with_recovery)
    from alink_tpu.common.resilience import RetryPolicy
    from alink_tpu.io.kafka import MemoryKafkaBroker
    from alink_tpu.operator.stream import (KafkaSinkStreamOp,
                                           TableSourceStreamOp)
    from alink_tpu.operator.stream.windows import TumbleTimeWindowStreamOp

    rng = np.random.RandomState(0)
    t = MTable({"ts": np.arange(rows, dtype=np.float64), "v": rng.rand(rows)})
    chunk, epoch_chunks = 512, 4

    def make_window():
        return TumbleTimeWindowStreamOp(
            timeCol="ts", windowTime=float(chunk * 2),
            clause="sum(v) as sv, count(*) as c")

    def job(tag, ckdir):
        return RecoverableStreamJob(
            source=TableSourceStreamOp(t, chunkSize=chunk),
            chains=[([make_window()],
                     [KafkaSinkStreamOp(
                         bootstrapServers=f"memory://bench-rec-{tag}",
                         topic="w")])],
            checkpoint_dir=ckdir, epoch_chunks=epoch_chunks)

    # raw drain of the same pipeline INCLUDING the sink (row encoding +
    # publish), so the tax ratio isolates the checkpoint machinery itself
    # rather than charging sink serialization to it; one un-timed warmup
    # drain first so the GroupBy/jit cold start doesn't masquerade as tax
    def raw_drain(tag, table):
        MemoryKafkaBroker.named(f"bench-rec-{tag}")
        sink = KafkaSinkStreamOp(
            bootstrapServers=f"memory://bench-rec-{tag}", topic="w")
        it = sink._stream_impl(make_window()._stream_impl(
            TableSourceStreamOp(table, chunkSize=chunk)._stream_impl()))
        return sum(1 for _ in it)

    raw_drain("warm", t.slice(0, chunk * 2))
    t0 = time.perf_counter()
    raw_out = raw_drain("raw", t)
    raw_wall = time.perf_counter() - t0

    faults.clear()
    MemoryKafkaBroker.named("bench-rec-clean")
    ck_clean = tempfile.mkdtemp(prefix="alink-rec-")
    t0 = time.perf_counter()
    clean = run_with_recovery(
        lambda: job("clean", ck_clean),
        RetryPolicy(max_attempts=3, base_delay=0.01))
    clean_wall = time.perf_counter() - t0

    MemoryKafkaBroker.named("bench-rec-crash")
    ck_crash = tempfile.mkdtemp(prefix="alink-rec-")
    mid_chunk = (rows // chunk) // 2
    faults.install(faults.FaultSpec.parse(
        f"recovery:count=1,kinds=crash,match=chunk{mid_chunk}", seed=7))
    t0 = time.perf_counter()
    try:
        crashed = run_with_recovery(
            lambda: job("crash", ck_crash),
            RetryPolicy(max_attempts=5, base_delay=0.01))
    finally:
        faults.clear()
    crash_wall = time.perf_counter() - t0

    parity = (MemoryKafkaBroker.named("bench-rec-clean")._topics.get("w")
              == MemoryKafkaBroker.named("bench-rec-crash")._topics.get("w"))
    snap = metrics.timer_stats("recovery.snapshot_s") or {}
    commit = metrics.timer_stats("recovery.commit_s") or {}
    restore = metrics.timer_stats("recovery.restore_s") or {}
    return {
        "rows": rows, "windows_emitted": raw_out,
        "raw_wall_s": round(raw_wall, 3),
        "recovered_wall_s": round(clean_wall, 3),
        "checkpoint_tax": round(clean_wall / raw_wall, 3)
        if raw_wall > 0 else None,
        "epochs": clean.get("epochs"),
        "snapshot_ms_per_epoch": round(snap.get("mean_s", 0.0) * 1e3, 3),
        "commit_ms_per_epoch": round(commit.get("mean_s", 0.0) * 1e3, 3),
        "crash_parity_bit_identical": parity,
        "crashed_wall_s": round(crash_wall, 3),
        "restore_latency_ms": round(restore.get("mean_s", 0.0) * 1e3, 3),
        "chunks_replayed_on_restart": crashed.get("replayed_chunks"),
        "counters": recovery_summary(),
    }


def bench_elastic(rows=24_000):
    """Elastic streaming (common/elastic.py): a sustained keyed windowed
    stream under a load spike. The spike is injected into the
    backpressure SIGNAL (a scripted queue-lag schedule standing in for a
    live source's backlog — the data path, epoch runtime, and rescale
    machinery are all real): the controller scales 2→4 under sustained
    lag and back in when the spike passes. Reports rescale latency
    (barrier→resume), chunks replayed, throughput before/during/after
    the elastic window, and a bit-parity bit vs the fixed-parallelism
    run."""
    import tempfile

    from alink_tpu.common import faults
    from alink_tpu.common.elastic import (BackpressureController,
                                          ElasticStreamJob, elastic_summary)
    from alink_tpu.common.metrics import metrics
    from alink_tpu.common.mtable import MTable
    from alink_tpu.common.recovery import run_with_recovery
    from alink_tpu.common.resilience import RetryPolicy
    from alink_tpu.io.kafka import MemoryKafkaBroker
    from alink_tpu.operator.stream import (KafkaSinkStreamOp,
                                           TableSourceStreamOp)
    from alink_tpu.operator.stream.windows import TumbleTimeWindowStreamOp

    rng = np.random.RandomState(0)
    t = MTable({"ts": np.arange(rows, dtype=np.float64),
                "user": rng.randint(0, 64, rows).astype(np.int64),
                "v": rng.rand(rows)})
    chunk, epoch_chunks = 256, 4
    spike_epochs = (5, 9)  # lag injected on these epochs (inclusive lo)

    def chain():
        return [TumbleTimeWindowStreamOp(
            timeCol="ts", windowTime=float(chunk * 2), groupCols=["user"],
            clause="sum(v) as sv, count(*) as c")]

    def lag_fn(stats):
        lo, hi = spike_epochs
        if lo <= stats["epoch"] < hi:
            return 5.0    # backlog: sustained lag → scale out
        if stats["epoch"] < lo:
            return 0.02   # keeping up: in the hysteresis band, P holds
        return 0.0        # idle drain after the spike → scale back in

    def job(tag, ckdir, controller):
        return ElasticStreamJob(
            source=TableSourceStreamOp(t, chunkSize=chunk),
            chains=[(chain, [KafkaSinkStreamOp(
                bootstrapServers=f"memory://bench-el-{tag}", topic="w")])],
            checkpoint_dir=ckdir, key_col="user", parallelism=2,
            epoch_chunks=epoch_chunks, controller=controller)

    faults.clear()
    MemoryKafkaBroker.named("bench-el-fixed")
    t0 = time.perf_counter()
    run_with_recovery(
        lambda: job("fixed", tempfile.mkdtemp(prefix="alink-el-"), None),
        RetryPolicy(max_attempts=3, base_delay=0.01))
    fixed_wall = time.perf_counter() - t0

    MemoryKafkaBroker.named("bench-el-auto")
    t0 = time.perf_counter()
    summary = run_with_recovery(
        lambda: job("auto", tempfile.mkdtemp(prefix="alink-el-"),
                    BackpressureController(target_chunk_s=0.05, patience=2,
                                           cooldown_epochs=2,
                                           lag_fn=lag_fn)),
        RetryPolicy(max_attempts=3, base_delay=0.01))
    auto_wall = time.perf_counter() - t0

    parity = (MemoryKafkaBroker.named("bench-el-fixed")._topics.get("w")
              == MemoryKafkaBroker.named("bench-el-auto")._topics.get("w"))

    def seg_rows_per_s(stats, lo, hi):
        eps = [e for e in stats if lo <= e["epoch"] < hi and e["chunks"]]
        wall = sum(e["wall_s"] for e in eps)
        return round(sum(e["chunks"] for e in eps) * chunk / wall, 1) \
            if wall > 0 else None

    es = summary["epoch_stats"]
    lo, hi = spike_epochs
    resc = metrics.timer_stats("recovery.rescale_s") or {}
    return {
        "rows": rows,
        "fixed_wall_s": round(fixed_wall, 3),
        "elastic_wall_s": round(auto_wall, 3),
        "rescales": summary["rescales"],
        "rescale_latency_ms": round(resc.get("mean_s", 0.0) * 1e3, 3),
        "chunks_replayed": summary["replayed_chunks"],
        "rows_per_s_before_spike": seg_rows_per_s(es, 0, lo),
        "rows_per_s_during_spike": seg_rows_per_s(es, lo, hi + 2),
        "rows_per_s_after_spike": seg_rows_per_s(
            es, hi + 2, es[-1]["epoch"] + 1),
        "max_parallelism_reached": max(e["parallelism"] for e in es),
        "parity_bit_identical": parity,
        "counters": elastic_summary(),
    }


def bench_modelstream(rows=4_000):
    """Continuous model streaming (alink_tpu/modelstream/): an FTRL
    stream-train job publishing at every epoch barrier into a live
    ModelServer while a traffic thread keeps predicting against the
    swapping model. Reports publish→servable lag (p50/p99 of
    ``modelstream.lag_s``), hot-swap latency, publishes per epoch, the
    zero-trace bit (jit.trace delta across swaps after the first), and a
    parity bit (served row == LocalPredictor over the latest published
    blob). The gate pins parity, zero traces, every-epoch publishing,
    and the staleness bound (lag p99 within LAG_BOUND_S)."""
    import tempfile
    import threading

    from alink_tpu.common import faults
    from alink_tpu.common.metrics import metrics
    from alink_tpu.common.mtable import MTable
    from alink_tpu.common.recovery import (RecoverableStreamJob,
                                           run_with_recovery)
    from alink_tpu.common.resilience import RetryPolicy
    from alink_tpu.modelstream import ModelStreamPublisher
    from alink_tpu.operator.stream import (DatahubSinkStreamOp,
                                           FtrlTrainStreamOp,
                                           TableSourceStreamOp)
    from alink_tpu.pipeline.local_predictor import LocalPredictor
    from alink_tpu.serving.router import ModelServer

    LAG_BOUND_S = 30.0  # staleness bound: epoch start → servable swap
    rng = np.random.RandomState(0)
    t = MTable({"x0": rng.rand(rows), "x1": rng.rand(rows),
                "label": (rng.rand(rows) > 0.5).astype(np.int64)})
    schema = "x0 DOUBLE, x1 DOUBLE"
    store_dir = tempfile.mkdtemp(prefix="alink-ms-")

    server = ModelServer()
    pub = ModelStreamPublisher(store_dir, "ftrl-bench", server=server,
                               input_schema=schema, keep=3)

    stop = threading.Event()
    traffic = {"hits": 0, "misses": 0}

    def drive():
        while not stop.is_set():
            try:
                server.predict("ftrl-bench", [0.3, 0.7])
                traffic["hits"] += 1
            except Exception:
                traffic["misses"] += 1  # model not swapped in yet
            stop.wait(0.002)

    def job():
        return RecoverableStreamJob(
            source=TableSourceStreamOp(t, chunkSize=128),
            chains=[([FtrlTrainStreamOp(featureCols=["x0", "x1"],
                                        labelCol="label")],
                     [DatahubSinkStreamOp(endpoint="memory://bench-ms",
                                          topic="m")])],
            checkpoint_dir=tempfile.mkdtemp(prefix="alink-ms-ck-"),
            epoch_chunks=4, publishers=[pub])

    faults.clear()
    thread = threading.Thread(target=drive, daemon=True)
    thread.start()
    t0 = time.perf_counter()
    try:
        summary = run_with_recovery(job, RetryPolicy(max_attempts=3,
                                                     base_delay=0.01))
    finally:
        stop.set()
        thread.join(timeout=5)
    wall = time.perf_counter() - t0

    epochs = summary["epochs"]
    publishes = metrics.counter("modelstream.publishes")
    trace_delta = metrics.counter("modelstream.swap_trace_delta")
    lag = metrics.histogram("modelstream.lag_s") or {}
    swap = metrics.timer_stats("modelstream.swap_s") or {}

    latest = pub.store.latest()
    served = served_local = None
    if latest is not None:
        blob = pub.store.blob_path(latest[0])
        served = tuple(server.predict("ftrl-bench", [0.3, 0.7]))
        served_local = tuple(
            LocalPredictor(blob, schema).predict_row([0.3, 0.7]))
    parity = served is not None and served == served_local
    zero_trace = publishes >= 3 and trace_delta == 0
    lag_ok = lag.get("p99") is not None and lag["p99"] <= LAG_BOUND_S
    return {
        "rows": rows,
        "wall_s": round(wall, 3),
        "epochs": epochs,
        "publishes": publishes,
        "publishes_per_epoch": round(publishes / epochs, 3) if epochs
        else None,
        "lag_p50_ms": round(lag["p50"] * 1e3, 3) if lag.get("p50")
        is not None else None,
        "lag_p99_ms": round(lag["p99"] * 1e3, 3) if lag.get("p99")
        is not None else None,
        "swap_latency_ms": round(swap.get("mean_s", 0.0) * 1e3, 3),
        "swaps": swap.get("count", 0),
        "traffic_hits": traffic["hits"],
        "traffic_misses": traffic["misses"],
        "zero_trace_swaps": zero_trace,
        "parity_bit_identical": parity,
        "gate": {
            "ok": bool(parity and zero_trace and lag_ok
                       and publishes == epochs),
            "parity": parity,
            "zero_trace": zero_trace,
            "lag_p99_within_bound_s": LAG_BOUND_S if lag_ok else False,
            "published_every_epoch": publishes == epochs,
        },
    }


def bench_compile():
    """Shape-stable execution layer (common/jitcache.py): the compile-tax
    readout tracked across BENCH rounds. Runs the kmeans_iris pipeline and a
    digits-sized softmax predict twice each — cold wall includes trace +
    compile (or persistent-cache load), warm is pure cache-hit reuse — and
    reports the per-workload trace/compile counts plus the process-wide
    program-cache hit rate. The steady-state contract the tests enforce
    (zero new traces on a warm second run) shows up here as
    ``*_warm_compiles == 0``."""
    from alink_tpu.common.jitcache import compile_summary
    from alink_tpu.common.metrics import metrics
    from alink_tpu.common.mtable import MTable
    from alink_tpu.operator.batch import (SoftmaxPredictBatchOp,
                                          SoftmaxTrainBatchOp)
    from alink_tpu.operator.batch.base import (CsvSourceBatchOp,
                                               TableSourceBatchOp)
    from alink_tpu.pipeline import KMeans, Pipeline

    def counted(fn):
        c0 = metrics.counter("jit.compile")
        t0 = time.perf_counter()
        fn()
        return (round(time.perf_counter() - t0, 3),
                metrics.counter("jit.compile") - c0)

    def cold_warm(fn):
        cold_s, cold_c = counted(fn)
        warm_s, warm_c = counted(fn)
        return {"cold_wall_s": cold_s, "warm_wall_s": warm_s,
                "cold_compiles": cold_c, "warm_compiles": warm_c}

    out = {}
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "data", "iris.csv")
    iris = CsvSourceBatchOp(
        filePath=path,
        schemaStr="sl double, sw double, pl double, pw double, species string")

    def kmeans_fit():
        pipe = Pipeline(KMeans(k=3, maxIter=50,
                               featureCols=["sl", "sw", "pl", "pw"],
                               predictionCol="pred"))
        pipe.fit(iris).transform(iris).collect()

    dpath = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "data", "digits.csv")
    dcols = [f"p{i}" for i in range(64)]
    schema = ", ".join(f"{c} double" for c in dcols) + ", label long"
    digits = CsvSourceBatchOp(filePath=dpath, schemaStr=schema).collect()

    def softmax_fit():
        m = SoftmaxTrainBatchOp(
            featureCols=dcols, labelCol="label", maxIter=30,
        ).link_from(TableSourceBatchOp(digits))
        SoftmaxPredictBatchOp().link_from(
            m, TableSourceBatchOp(digits)).collect()

    for name, fn in (("kmeans_iris", kmeans_fit),
                     ("softmax_mnist", softmax_fit)):
        try:  # one failing workload must not sink the whole extra
            out[name] = cold_warm(fn)
        except Exception as e:
            out[name] = {"error": f"{type(e).__name__}: {e}"[:200]}

    summary = compile_summary()
    out["program_cache"] = {
        "programs": summary["programs"],
        "hit_rate": summary["hit_rate"],
        "traces": summary["counters"].get("jit.trace", 0),
        "compiles": summary["counters"].get("jit.compile", 0),
        "compile_s": (metrics.timer_stats("jitcache.compile_s")
                      or {}).get("total_s")}
    return out


_COLDSTART_CHILD = '''
import json, os, sys, time

t_start = time.perf_counter()
sys.path.insert(0, {repo!r})
import numpy as np

import alink_tpu  # noqa: F401 — enables the persistent cache from env
from alink_tpu.common.metrics import metrics
from alink_tpu.common.profiling import program_costs
from alink_tpu.operator.batch.base import CsvSourceBatchOp
from alink_tpu.pipeline import KMeans, Pipeline

t_import = time.perf_counter()
src = CsvSourceBatchOp(
    filePath={csv!r},
    schemaStr="sl double, sw double, pl double, pw double, species string")
pipe = Pipeline(KMeans(k=3, maxIter=50, featureCols=["sl", "sw", "pl", "pw"],
                       predictionCol="pred"))
out = pipe.fit(src).transform(src).collect()
t_first = time.perf_counter()
print(json.dumps({{
    "import_s": round(t_import - t_start, 3),
    "first_result_s": round(t_first - t_import, 3),
    "total_s": round(t_first - t_start, 3),
    "persist_hit": metrics.counter("jit.persist_hit"),
    "persist_miss": metrics.counter("jit.persist_miss"),
    "persist_error": metrics.counter("jit.persist_error"),
    "compiles": metrics.counter("jit.compile"),
    "traces": metrics.counter("jit.trace"),
    "profile_records": len(program_costs(resolve=False)),
    "labels": [int(x) for x in np.asarray(out.col("pred"))],
}}))
'''


def bench_coldstart():
    """Zero-cold-start gate: compiled programs must survive process death.
    Spawns the kmeans_iris workload in TWO fresh interpreters sharing one
    ``ALINK_COMPILE_CACHE_DIR``: the first pays real backend compiles and
    populates the cache; the second must reach its first result on
    persist-hits (``jit.persist_hit > 0``), bit-identical outputs, with the
    verdict judged by the benchstats machinery (a cold-threshold
    compare of the two first-result walls). ``ratio_vs_warm`` relates the
    second process's workload wall to this (warm) process's in-memory wall
    — the rollout latency a replica autoscale-up actually pays."""
    import shutil
    import subprocess
    import sys
    import tempfile

    from alink_tpu.common.benchstats import COLD_THRESHOLD, compare_samples
    from alink_tpu.common.jitcache import _persist_entries, persist_cap_bytes

    repo = os.path.dirname(os.path.abspath(__file__))
    csv = os.path.join(repo, "data", "iris.csv")
    cache_dir = tempfile.mkdtemp(prefix="alink-coldstart-")
    script = _COLDSTART_CHILD.format(repo=repo, csv=csv)

    def run_child(tag):
        env = dict(os.environ)
        env["ALINK_COMPILE_CACHE_DIR"] = cache_dir
        env.setdefault("JAX_PLATFORMS", "cpu")
        proc = subprocess.run([sys.executable, "-c", script], env=env,
                              capture_output=True, text=True, timeout=600)
        if proc.returncode != 0:
            raise RuntimeError(
                f"coldstart child {tag} failed: {proc.stderr[-1500:]}")
        return json.loads(proc.stdout.strip().splitlines()[-1])

    try:
        first = run_child("first")
        second = run_child("second")
    finally:
        # the same accounting persist_summary() reports, on an explicit dir
        on_disk = _persist_entries(cache_dir)
        entries = len(on_disk)
        cache_bytes = sum(e[2] for e in on_disk)
        shutil.rmtree(cache_dir, ignore_errors=True)

    # the warm reference: the same workload in THIS process, already
    # compiled (bench_compile warms it earlier in a full driver run)
    warm = bench_kmeans_iris()["wall_clock_warm_s"]
    gate = compare_samples([first["first_result_s"]],
                           [second["first_result_s"]],
                           noise_floor=COLD_THRESHOLD)
    bit_identical = first["labels"] == second["labels"]
    out = {
        "first_process": {k: v for k, v in first.items() if k != "labels"},
        "second_process": {k: v for k, v in second.items() if k != "labels"},
        "cold_first_result_s": first["first_result_s"],
        "second_cold_first_result_s": second["first_result_s"],
        "warm_wall_s": warm,
        "ratio_vs_warm_cold": round(first["first_result_s"] / warm, 1)
        if warm else None,
        "ratio_vs_warm_second": round(second["first_result_s"] / warm, 1)
        if warm else None,
        "persist_hits_second_process": second["persist_hit"],
        "cache_entries": entries,
        "cache_mb": round(cache_bytes / 1e6, 2),
        "cache_cap_mb": round(persist_cap_bytes() / 1e6, 1),
        "bit_identical": bit_identical,
        "second_vs_first_verdict": gate["verdict"],
        "second_vs_first_delta_pct": gate["delta_pct"],
        "gate": {
            "persist_hit_ok": second["persist_hit"] > 0,
            "no_persist_errors": second["persist_error"] == 0,
            "bit_identical": bit_identical,
            # wall verdict: on CPU containers trace time floors both
            # processes (XLA:CPU compiles these programs in ~0.1s, so the
            # skip is noise-level); the hard requirement is "never slower"
            # — the big wall win is the TPU chip's 20-40s compiles
            "second_not_slower": gate["verdict"] != "regression",
        },
    }
    out["gate"]["ok"] = all(out["gate"].values())
    return out


def bench_serving(clients=8, rows_per_client=400):
    """Online serving tier (alink_tpu/serving): sustained concurrent-client
    drill against one loaded pipeline model. ``clients`` threads submit
    single-row predict requests as fast as completions allow; the router
    coalesces them into bucket-ladder micro-batches. Reports rows/s,
    batch-fill ratio, request-latency p50/p90/p99, the jit trace delta over
    the sustained window (target: 0 after load-time warmup), and a
    past-capacity shed probe (bounded queue, counted rejections).

    A second pass re-runs the same drill against the SAME model loaded
    with ``precision="int8"`` (calibrated + accuracy-band-gated at load):
    the ``precision`` block reports fp32-vs-int8 rows/s and client-side
    p99, the load's band-gate verdict (``band_ok`` must be green), the
    label ``accuracy_delta`` / numeric ``accuracy_band`` readouts
    (directionless in ``--compare``, like ``parity_max_diff``), and the
    bit-identity gate: the precision-unset fp32 load must serve
    byte-identical rows to a serial LocalPredictor."""
    import threading

    from alink_tpu.common.metrics import metrics
    from alink_tpu.common.mtable import MTable
    from alink_tpu.pipeline import (NaiveBayes, Pipeline, StandardScaler,
                                    VectorAssembler)
    from alink_tpu.serving import (AkServingOverloadException, ModelServer,
                                   ServingConfig, serving_summary)

    rng = np.random.RandomState(0)
    X = np.concatenate([rng.normal(c, 0.4, size=(200, 4))
                        for c in [(0, 0, 0, 0), (2, 2, 2, 2)]])
    y = np.repeat(["neg", "pos"], 200)
    feats = ["f0", "f1", "f2", "f3"]
    t = MTable({f"f{i}": X[:, i] for i in range(4)}).with_column("label", y)
    model = Pipeline(
        StandardScaler(selectedCols=feats),
        VectorAssembler(selectedCols=feats, outputCol="vec"),
        NaiveBayes(vectorCol="vec", labelCol="label", predictionCol="pred"),
    ).fit(t)
    schema = "f0 double, f1 double, f2 double, f3 double"

    srv = ModelServer(ServingConfig(queue_depth=512, max_batch_rows=64,
                                    flush_deadline_s=0.002))
    try:
        t_load0 = time.perf_counter()
        load_info = srv.load("bench", model, schema,
                             warmup_rows=[tuple(X[0])])
        load_s = time.perf_counter() - t_load0

        traces0 = metrics.counter("jit.trace")
        rows = [tuple(r) for r in X]

        def drill(server, mname):
            lat: list = []
            lat_lock = threading.Lock()

            def client(cid):
                mine = []
                for i in range(rows_per_client):
                    r0 = time.perf_counter()
                    server.predict(mname,
                                   rows[(cid * 131 + i * 7) % len(rows)],
                                   timeout=120)
                    mine.append(time.perf_counter() - r0)
                with lat_lock:
                    lat.extend(mine)

            ths = [threading.Thread(target=client, args=(c,))
                   for c in range(clients)]
            w0 = time.perf_counter()
            for th in ths:
                th.start()
            for th in ths:
                th.join()
            return time.perf_counter() - w0, np.asarray(lat)

        wall, lat_f = drill(srv, "bench")
        traces_delta = metrics.counter("jit.trace") - traces0
        stats = serving_summary(srv)
        mstat = stats["models"][0]
        req_hist = stats["histograms"].get("serving.request_s") or {}

        # ---- quantized pass: same model, same drill, int8 policy --------
        from alink_tpu.pipeline import LocalPredictor

        calib_rows = [tuple(r) for r in X[::25]]  # spans both clusters
        info8 = srv.load("bench8", model, schema, warmup_rows=calib_rows,
                         precision="int8")
        band = (info8.get("precision") or {}).get("band_report") or {}
        traces8_0 = metrics.counter("jit.trace")
        wall8, lat_q = drill(srv, "bench8")
        traces8_delta = metrics.counter("jit.trace") - traces8_0
        # label agreement + bit-identity gate over one deterministic sweep
        lp = LocalPredictor(model, schema, cache_plan=False)
        serial = [lp.predict_table(
            MTable.from_rows([r], schema)).get_row(0) for r in rows[:100]]
        out_f = [srv.predict("bench", r, timeout=120) for r in rows[:100]]
        out_q = [srv.predict("bench8", r, timeout=120) for r in rows[:100]]
        agree = float(np.mean([a[-1] == b[-1]
                               for a, b in zip(out_q, out_f)]))
        total = clients * rows_per_client
        precision_block = {
            "policy": (info8.get("precision") or {}).get("policy"),
            "band_ok": band.get("ok"),
            # directionless in --compare (metric_direction → None), like
            # parity_max_diff: near-zero diffs vs the fp32 baseline
            "accuracy_delta": round(1.0 - agree, 6),
            "accuracy_band": band.get("max_rel_diff"),
            "fp32_rows_per_sec": round(total / wall, 1),
            "int8_rows_per_sec": round(total / wall8, 1),
            "fp32_request_p99_ms": round(
                float(np.percentile(lat_f, 99)) * 1e3, 3),
            "int8_request_p99_ms": round(
                float(np.percentile(lat_q, 99)) * 1e3, 3),
            "int8_traces_during_drill": traces8_delta,
            # knob-off gate: the precision-unset load serves byte-identical
            # rows to a serial LocalPredictor
            "bit_identical_fp32": out_f == serial,
        }

        # saturation probe: flood far past the queue bound with async
        # submits; shed must be counted and accepted work must complete
        srv2 = ModelServer(ServingConfig(queue_depth=32, max_batch_rows=32,
                                         flush_deadline_s=0.05))
        srv2.load("sat", model, schema, warmup_rows=[tuple(X[0])])
        futs, shed = [], 0
        for i in range(2000):
            try:
                futs.append(srv2.submit("sat", rows[i % len(rows)]))
            except AkServingOverloadException:
                shed += 1
        completed = sum(1 for f in futs if f.result(120) is not None)
        srv2.close()

        return {
            "clients": clients,
            "rows": total,
            "rows_per_sec": round(total / wall, 1),
            "load_s": round(load_s, 3),
            "warmup": load_info["warmup"],
            "batch_fill": mstat["batch_fill"],
            "batches": mstat["batches"],
            "request_p50_ms": round((req_hist.get("p50") or 0) * 1e3, 3),
            "request_p90_ms": round((req_hist.get("p90") or 0) * 1e3, 3),
            "request_p99_ms": round((req_hist.get("p99") or 0) * 1e3, 3),
            "traces_during_drill": traces_delta,  # sustained window; 0 = contract held
            "precision": precision_block,
            "saturation": {"submitted": 2000, "shed": shed,
                           "accepted_completed": completed},
        }
    finally:
        srv.close()


def bench_fleet(clients=6, rows_per_client=60):
    """Fault-tolerant serving fleet (alink_tpu/serving/fleet): multi-process
    replica scaling at N∈{1,2,4} (rows/s + request p99 per N, bit-parity vs
    the single-process ModelServer over the same rows), then a chaos drill —
    one replica killed mid-batch at load via the ``replica`` fault point —
    reporting failover count, recovery time back to full ready strength,
    and the delivery gate: every accepted request either completed with the
    serial answer or shed with a typed error; none lost. Zero-trace gate:
    replica trace deltas stay 0 (all warmup from the ``.ak.warmup.json``
    sidecar, never live traffic). Observability phase: tracing off-vs-on
    through the full frontdoor→replica path (interleaved, benchstats-judged
    delta + bit-parity) and the stitched-trace gate — the frontdoor trace
    must contain at least one replica-process-tagged span."""
    import shutil
    import tempfile
    import threading

    from alink_tpu.common.exceptions import (AkCircuitOpenException,
                                             AkDeadlineExceededException)
    from alink_tpu.common.mtable import MTable
    from alink_tpu.pipeline import (NaiveBayes, Pipeline, StandardScaler,
                                    VectorAssembler)
    from alink_tpu.serving import (AkServingOverloadException, FleetConfig,
                                   ModelServer, ServingFleet)

    rng = np.random.RandomState(0)
    X = np.concatenate([rng.normal(c, 0.4, size=(200, 4))
                        for c in [(0, 0, 0, 0), (2, 2, 2, 2)]])
    y = np.repeat(["neg", "pos"], 200)
    feats = ["f0", "f1", "f2", "f3"]
    t = MTable({f"f{i}": X[:, i] for i in range(4)}).with_column("label", y)
    model = Pipeline(
        StandardScaler(selectedCols=feats),
        VectorAssembler(selectedCols=feats, outputCol="vec"),
        NaiveBayes(vectorCol="vec", labelCol="label", predictionCol="pred"),
    ).fit(t)
    schema = "f0 double, f1 double, f2 double, f3 double"
    tmp = tempfile.mkdtemp(prefix="alink_bench_fleet_")
    rows = [tuple(r) for r in X]
    try:
        path = os.path.join(tmp, "model.ak")
        model.save(path)
        # single-process ground truth; the load also writes the warmup
        # sidecar every fleet replica warms from
        srv = ModelServer()
        srv.load("m", path, schema, warmup_rows=[tuple(X[0])])
        serial = [srv.predict("m", r) for r in rows[:32]]
        srv.close()

        typed = (AkServingOverloadException, AkCircuitOpenException,
                 AkDeadlineExceededException)

        def drill(fleet, lat, mismatches):
            shed, lost = [0], []

            def client(cid):
                for i in range(rows_per_client):
                    k = (cid * 131 + i * 7) % len(rows)
                    t0 = time.perf_counter()
                    try:
                        got = fleet.predict("m", rows[k], timeout=60)
                        lat.append(time.perf_counter() - t0)
                        if k < 32 and got != serial[k]:
                            mismatches.append(k)
                    except typed:
                        shed[0] += 1
                    except Exception as e:
                        lost.append(f"{type(e).__name__}: {e}"[:120])

            threads = [threading.Thread(target=client, args=(c,))
                       for c in range(clients)]
            t0 = time.perf_counter()
            for th in threads:
                th.start()
            for th in threads:
                th.join()
            return time.perf_counter() - t0, shed[0], lost

        def trace_deltas(fleet):
            time.sleep(3 * fleet._cfg.heartbeat_s + 0.2)  # let hbs land
            return [r["trace_delta"]
                    for r in fleet.fleet_summary()["replicas"]]

        scales, parity_ok, zero_trace, all_lost = {}, True, True, []
        for n in (1, 2, 4):
            lat, mism = [], []
            with ServingFleet(FleetConfig(
                    replicas=n, heartbeat_s=0.2,
                    heartbeat_timeout_s=1.5)) as fleet:
                fleet.load("m", path, schema)
                wall, shed, lost = drill(fleet, lat, mism)
                deltas = trace_deltas(fleet)
            total = clients * rows_per_client
            parity_ok = parity_ok and not mism
            zero_trace = zero_trace and all(d == 0 for d in deltas)
            all_lost += lost
            scales[str(n)] = {
                "rows_per_sec": round((total - shed - len(lost)) / wall, 1),
                "request_p99_ms": round(
                    float(np.percentile(lat, 99)) * 1e3, 3) if lat else None,
                "shed": shed,
                "trace_deltas": deltas,
            }

        # chaos drill: r1's first incarnation (gen 2) dies on its first
        # routed batch; the front-end re-dispatches, the supervisor
        # respawns it warm from the sidecar
        lat, mism = [], []
        with ServingFleet(FleetConfig(
                replicas=2, heartbeat_s=0.2, heartbeat_timeout_s=1.0,
                worker_env={"ALINK_FAULT_SPEC":
                            "replica:count=1,kinds=kill_mid_batch,"
                            "match=r1.g2.batch"})) as fleet:
            fleet.load("m", path, schema)
            t_drill0 = time.perf_counter()
            wall, shed, lost = drill(fleet, lat, mism)
            all_lost += lost
            recovery_s = None
            deadline = time.perf_counter() + 60
            while time.perf_counter() < deadline:
                s = fleet.fleet_summary()
                if s["states"].get("ready") == 2 and all(
                        r["synced"].get("m") for r in s["replicas"]):
                    recovery_s = time.perf_counter() - t_drill0
                    break
                time.sleep(0.1)
            for k in range(16):  # post-recovery parity
                if fleet.predict("m", rows[k], timeout=60) != serial[k]:
                    mism.append(k)
            deltas = trace_deltas(fleet)
            summary = fleet.fleet_summary()
        parity_ok = parity_ok and not mism
        zero_trace = zero_trace and all(d == 0 for d in deltas)
        counters = summary["counters"]
        respawn_loads = [ld for r in summary["replicas"]
                         for ld in (r["loads"] or []) if r["gen"] > 2]
        kill = {
            "shed": shed,
            "lost": all_lost,
            "failovers": counters.get("fleet.failovers", 0),
            "respawns": counters.get("fleet.respawns", 0),
            "recovery_s": round(recovery_s, 2) if recovery_s else None,
            "respawn_warmup": [ld.get("warmup_source")
                               for ld in respawn_loads],
        }
        # ---- observability phase: tracing off vs on through the SAME
        # frontdoor→replica path. Two fleets (workers inherit the flag at
        # spawn), thunks interleaved so container drift charges both flags
        # equally; the supervisor-side flag flips with the thunk so the
        # frontend span + wire context toggle together with the replicas.
        from alink_tpu.common.benchstats import (compare_samples,
                                                 measure_interleaved)
        from alink_tpu.common.tracing import job_report, tracer

        prev_flag = os.environ.get("ALINK_TRACING")
        tfleets, touts = {}, {}
        try:
            for flag in ("off", "on"):
                tfleets[flag] = ServingFleet(FleetConfig(
                    replicas=2, heartbeat_s=0.2, heartbeat_timeout_s=1.5,
                    worker_env={"ALINK_TRACING": flag}))
                tfleets[flag].start()
                tfleets[flag].load("m", path, schema)

            def traced(flag):
                def thunk():
                    os.environ["ALINK_TRACING"] = flag
                    touts[flag] = [tfleets[flag].predict("m", rows[k],
                                                         timeout=60)
                                   for k in range(32)]
                return thunk

            for flag in ("off", "on"):  # warmup outside both windows
                traced(flag)()
            walls = measure_interleaved(
                {"off": traced("off"), "on": traced("on")},
                repeats=5, warmup=0)
            trace_overhead = compare_samples(walls["off"], walls["on"])
            trace_parity = (touts["off"] == touts["on"]
                            and touts["off"] == serial[:32])

            # stitched-trace gate: one more traced predict, then poll the
            # frontdoor trace until a replica-proc-tagged span lands in it
            # (the replica batch spans ride the heartbeat relay)
            os.environ["ALINK_TRACING"] = "on"
            assert tfleets["on"].predict("m", rows[0],
                                         timeout=60) == serial[0]
            # newest fleet.request root, not last_trace_id(): relayed
            # replica load spans are local roots and can land right
            # after the predict, shadowing it
            tid = next(s["trace_id"] for s in reversed(tracer.spans())
                       if s["name"] == "fleet.request")

            def _stitched():
                def walk(nodes):
                    for nd in nodes:
                        yield nd
                        yield from walk(nd.get("children") or [])
                return any(nd.get("proc")
                           for nd in walk(job_report(tid).get("tree") or []))

            stitched = False
            deadline = time.perf_counter() + 20
            while time.perf_counter() < deadline:
                if _stitched():
                    stitched = True
                    break
                time.sleep(0.1)
        finally:
            for fl in tfleets.values():
                try:
                    fl.stop()
                except Exception:
                    pass
            if prev_flag is None:
                os.environ.pop("ALINK_TRACING", None)
            else:
                os.environ["ALINK_TRACING"] = prev_flag

        out = {
            "clients": clients,
            "rows_per_client": rows_per_client,
            "scales": scales,
            "kill_drill": kill,
            "tracing": {
                "overhead": trace_overhead,
                "bit_parity_on_vs_off": trace_parity,
                "stitched_trace_id": tid,
            },
            "gate": {
                "parity": parity_ok,
                "zero_trace": zero_trace,
                "clean_shed": not all_lost,
                "recovered": (recovery_s is not None
                              and kill["respawns"] >= 1
                              and kill["respawn_warmup"] == ["sidecar"]),
                "tracing_parity": trace_parity,
                "stitched": stitched,
            },
        }
        out["gate"]["ok"] = all(out["gate"].values())
        return out
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def bench_observability(repeats=3):
    """Unified tracing & telemetry layer (common/tracing.py + the metrics
    histogram/Prometheus export): run kmeans_iris with ALINK_TRACING=off vs
    on and report the overhead delta (budget: <3% wall, README-documented),
    the tracing-on vs -off bit-parity of predictions, the exported-metric
    counts by family, and the span count of the run's job_report."""
    from alink_tpu.common.metrics import metrics
    from alink_tpu.common.tracing import job_report
    from alink_tpu.operator.batch.base import CsvSourceBatchOp
    from alink_tpu.pipeline import KMeans, Pipeline

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "data", "iris.csv")
    src = CsvSourceBatchOp(
        filePath=path,
        schemaStr="sl double, sw double, pl double, pw double, species string")

    def kmeans_once():
        pipe = Pipeline(KMeans(
            k=3, maxIter=50, featureCols=["sl", "sw", "pl", "pw"],
            predictionCol="pred"))
        out = pipe.fit(src).transform(src).collect()
        return np.asarray(out.col("pred"))

    def mapper_dag_once():
        # fallback workload when kmeans cannot run (e.g. a container whose
        # jax dropped shard_map): the same executor + jit surface without a
        # mesh — branches on the DAG pool plus a fused block-kernel chain
        from alink_tpu.common.mtable import AlinkTypes, MTable
        from alink_tpu.mapper.base import BlockKernelMapper
        from alink_tpu.operator.batch import TableSourceBatchOp
        from alink_tpu.operator.batch.utils import MapBatchOp

        def affine(col, out_col, a, b):
            class _M(BlockKernelMapper):
                def kernel(self, schema):
                    return ([col], [out_col], [AlinkTypes.DOUBLE],
                            lambda X: X * a + b)

            class _Op(MapBatchOp):
                mapper_cls = _M

            return _Op()

        rng = np.random.RandomState(0)
        t_src = TableSourceBatchOp(
            MTable({"x": rng.rand(2_000_000), "y": rng.rand(2_000_000)}))
        t_src.apply_func(
            lambda m: MTable({"y": np.asarray(m.col("y")) * 2.0}),
            out_schema="y double").lazy_collect(lambda m: None)
        chain = affine("x", "x1", 2.0, 1.0).link_from(t_src)
        chain = affine("x1", "x2", 0.5, -3.0).link_from(chain)
        chain = affine("x2", "x3", 4.0, 0.25).link_from(chain)
        return np.asarray(chain.collect().col("x3"))

    workload, run_once = "kmeans_iris", kmeans_once
    try:
        run_once()  # compile / program-cache warm, outside both windows
    except Exception:
        workload, run_once = "mapper_dag", mapper_dag_once
        run_once()

    # interleave off/on repetitions (min per flag): a block of off-runs
    # followed by a block of on-runs would charge allocator/page-cache
    # drift between the blocks to tracing
    walls = {"off": [], "on": []}
    outs = {}
    prev = os.environ.get("ALINK_TRACING")
    try:
        for _ in range(repeats):
            for flag in ("off", "on"):
                os.environ["ALINK_TRACING"] = flag
                t0 = time.perf_counter()
                outs[flag] = run_once()
                walls[flag].append(time.perf_counter() - t0)
    finally:
        if prev is None:
            os.environ.pop("ALINK_TRACING", None)
        else:
            os.environ["ALINK_TRACING"] = prev
    off_wall, on_wall = min(walls["off"]), min(walls["on"])

    parity = bool(np.array_equal(outs["off"], outs["on"]))
    report = job_report()  # the last traced run — BEFORE the span
    # microbenchmark below floods the ring with its own root spans

    # deterministic per-span microbenchmark: the end-to-end delta above
    # rides a shared container's noise floor (±5% on a 70ms workload); the
    # direct cost of one open+close is the stable number the <3% budget is
    # audited against (a job traces O(nodes) spans, so spans_per_job *
    # span_cost / wall is the true tax)
    from alink_tpu.common.tracing import trace_span

    os.environ["ALINK_TRACING"] = "on"
    try:
        for _ in range(100):
            with trace_span("bench.warm"):
                pass
        t0 = time.perf_counter()
        for _ in range(2000):
            with trace_span("bench.span"):
                pass
        span_us = (time.perf_counter() - t0) / 2000 * 1e6
    finally:
        if prev is None:
            os.environ.pop("ALINK_TRACING", None)
        else:
            os.environ["ALINK_TRACING"] = prev
    overhead = on_wall / off_wall - 1.0 if off_wall > 0 else None
    kinds: dict = {}
    for line in metrics.export_prometheus().splitlines():
        if line.startswith("# TYPE"):
            kinds[line.rsplit(" ", 1)[-1]] = \
                kinds.get(line.rsplit(" ", 1)[-1], 0) + 1
    return {
        "workload": workload,
        "tracing_off_wall_s": round(off_wall, 4),
        "tracing_on_wall_s": round(on_wall, 4),
        "overhead_pct": round(overhead * 100, 2)
        if overhead is not None else None,
        "within_3pct_budget": overhead is not None and overhead < 0.03,
        "span_cost_us": round(span_us, 2),
        "bit_parity_on_vs_off": parity,
        "exported_metrics": {"total": sum(kinds.values()), **kinds},
        "job_report": {
            "trace_id": report.get("trace_id"),
            "spans": len(report.get("spans", [])),
            "totals": report.get("totals"),
            "outcomes": report.get("outcomes"),
        },
    }


def bench_profiling(repeats=3, rows=300_000):
    """Performance observatory (common/profiling.py + common/benchstats.py):
    run a fused mapper-chain DAG with ALINK_PROFILING off vs on
    (interleaved, min per flag) and report the overhead delta plus off/on
    bit-parity — the instrumentation-never-changes-results contract — the
    per-kernel XLA cost/roofline table the observatory captured, and the
    benchstats perf gate smoked on two in-process measurements: a
    same-config pair must read no-change while a synthetic 20% slowdown
    must be flagged."""
    from alink_tpu.common.benchstats import (compare_samples,
                                             measure_interleaved, perf_gate)
    from alink_tpu.common.mtable import AlinkTypes, MTable
    from alink_tpu.common.profiling import profile_summary
    from alink_tpu.mapper.base import BlockKernelMapper
    from alink_tpu.operator.batch import TableSourceBatchOp
    from alink_tpu.operator.batch.utils import MapBatchOp

    def affine(col, out_col, a, b):
        class _M(BlockKernelMapper):
            def kernel(self, schema):
                return ([col], [out_col], [AlinkTypes.DOUBLE],
                        lambda X: X * a + b)

        class _Op(MapBatchOp):
            mapper_cls = _M

        return _Op()

    rng = np.random.RandomState(0)
    t = MTable({"x": rng.rand(rows)})

    def run_once():
        chain = affine("x", "x1", 2.0, 1.0).link_from(TableSourceBatchOp(t))
        chain = affine("x1", "x2", 0.5, -3.0).link_from(chain)
        return np.asarray(chain.collect().col("x2"))

    outs = {}

    def flagged(flag):
        def thunk():
            os.environ["ALINK_PROFILING"] = flag
            outs[flag] = run_once()

        return thunk

    prev = os.environ.get("ALINK_PROFILING")
    try:
        os.environ["ALINK_PROFILING"] = "on"
        run_once()  # trace + enqueue cost capture outside both windows
        walls = measure_interleaved(
            {"off": flagged("off"), "on": flagged("on")},
            repeats=max(repeats, 5), warmup=0)
        os.environ["ALINK_PROFILING"] = "on"
        summ = profile_summary(top=6)
    finally:
        if prev is None:
            os.environ.pop("ALINK_PROFILING", None)
        else:
            os.environ["ALINK_PROFILING"] = prev
    # judge the off-vs-on delta with the observatory's own variance-hardened
    # comparator: trimmed means + CI, so container jitter on a
    # milliseconds-scale workload reads "no-change" instead of a fake tax
    overhead = compare_samples(walls["off"], walls["on"])

    kernels = [{
        "kernel": k["kernel"],
        "calls": k["calls"],
        "flops": k["flops"],
        "bytes_accessed": k["bytes_accessed"],
        "peak_hbm_bytes": k["peak_hbm_bytes"],
        "achieved_gflops": round(k["achieved_flops_per_s"] / 1e9, 2)
        if k["achieved_flops_per_s"] else None,
        "intensity": k["roofline"]["arithmetic_intensity"],
        "bound": k["roofline"]["bound"],
    } for k in summ["kernels"]]

    gate_same = perf_gate(lambda: time.sleep(0.004),
                          lambda: time.sleep(0.004), repeats=7)
    gate_slow = perf_gate(lambda: time.sleep(0.004),
                          lambda: time.sleep(0.0048), repeats=7)
    return {
        "profiling_off_wall_s": overhead["base_mean_s"],
        "profiling_on_wall_s": overhead["cand_mean_s"],
        "overhead_pct": overhead["delta_pct"],
        "overhead_ci_pct": overhead["ci_pct"],
        "overhead_verdict": overhead["verdict"],
        "bit_parity_on_vs_off":
            bool(np.array_equal(outs["off"], outs["on"])),
        "device": summ["device"],
        "hbm_watermark": summ["hbm"],
        "kernels": kernels,
        "perf_gate": {
            "same_config_verdict": gate_same["verdict"],
            "synthetic_20pct_slowdown_verdict": gate_slow["verdict"],
            "slowdown_detail": gate_slow,
        },
    }


def bench_kernels(repeats=5):
    """Custom-kernel program (native/kernels.py + the Pallas kernels): the
    registry snapshot, the ranked roofline worst-offenders table
    (profiling.kernel_candidates), and per-kernel before/after — the fused
    SGNS block-gradient kernel vs the XLA _block_grads path and the flash
    attention kernel vs the XLA blockwise scan, each as its own cached
    program so the observatory captures both sides' roofline efficiency.
    Efficiency must move toward the ceiling and the wall must not regress
    on accelerator backends; on CPU containers both kernels run in Pallas
    interpret mode, so the verdicts report informationally
    (``wall_gate_applies`` false, the platform-aware-compare convention).
    Parity (atol 1e-5, the registry's pinned contract) gates everywhere."""
    import jax
    import jax.numpy as jnp

    from alink_tpu.common.benchstats import compare_samples, \
        measure_interleaved
    from alink_tpu.common.jitcache import cached_jit
    from alink_tpu.common.profiling import kernel_candidates, roofline
    from alink_tpu.dl.attention import blockwise_attention
    from alink_tpu.embedding.skipgram import _block_grads
    from alink_tpu.embedding.sgns_pallas import sgns_block_grads
    from alink_tpu.native.kernels import interpret_mode, registry

    platform = jax.devices()[0].platform
    wall_gate_applies = platform in ("tpu", "gpu")
    interp = interpret_mode()
    rng = np.random.RandomState(0)

    def bench_pair(kid, build, args_of, atol=1e-5):
        """Warm an XLA and a Pallas cached program of the same math, check
        parity, time interleaved, and read each side's roofline."""
        progs = {var: cached_jit(f"bench.{kid}_{var}", build, var)
                 for var in ("xla", "pallas")}
        args = args_of()
        outs = {var: jax.tree_util.tree_map(
            np.asarray, progs[var](*args)) for var in progs}
        flat_x = jax.tree_util.tree_leaves(outs["xla"])
        flat_p = jax.tree_util.tree_leaves(outs["pallas"])
        max_diff = max(float(np.abs(x - p).max())
                       for x, p in zip(flat_x, flat_p))
        walls = measure_interleaved(
            {var: (lambda v=var: jax.block_until_ready(progs[v](*args)))
             for var in progs}, repeats=repeats, warmup=1)
        delta = compare_samples(walls["xla"], walls["pallas"])
        eff = {}
        for var in progs:
            rows = [c for c in kernel_candidates(resolve=True)
                    if c["kernel"] == f"bench.{kid}_{var}"]
            eff[var] = rows[0]["efficiency"] if rows else None
        return {
            "parity_max_diff": max_diff,
            "parity_ok": bool(max_diff <= atol),
            "xla_wall_s": delta["base_mean_s"],
            "pallas_wall_s": delta["cand_mean_s"],
            "wall_delta_pct": delta["delta_pct"],
            "wall_verdict": delta["verdict"],
            "efficiency_before": eff["xla"],
            "efficiency_after": eff["pallas"],
        }

    # small enough that the interpret-mode grid emulation on CPU rounds
    # stays seconds-fast; real backends compile the Mosaic kernel
    B, negs, D = 1024, 4, 128

    def build_sgns(variant):
        def f(v, u_pos, u_neg):
            if variant == "pallas":
                return sgns_block_grads(v, u_pos, u_neg, interpret=interp)
            return _block_grads(v, u_pos, u_neg, D)

        return jax.jit(f)

    def sgns_args():
        return (jnp.asarray(rng.randn(B, D), jnp.float32),
                jnp.asarray(rng.randn(B, D), jnp.float32),
                jnp.asarray(rng.randn(B, negs, D), jnp.float32))

    b, s, h, d, blk = 4, 256, 4, 64, 128

    def build_attn(variant):
        def f(q, k, v, mask):
            prev = os.environ.get("ALINK_ATTN_PALLAS")
            # the knob is read at trace time; pin it to this variant for
            # the trace (variant is the cache-key static, so both programs
            # coexist)
            os.environ["ALINK_ATTN_PALLAS"] = \
                "1" if variant == "pallas" else "0"
            try:
                return blockwise_attention(q, k, v, mask, block_size=blk,
                                           causal=True)
            finally:
                if prev is None:
                    os.environ.pop("ALINK_ATTN_PALLAS", None)
                else:
                    os.environ["ALINK_ATTN_PALLAS"] = prev

        return jax.jit(f)

    def attn_args():
        return (jnp.asarray(rng.randn(b, s, h, d), jnp.float32),
                jnp.asarray(rng.randn(b, s, h, d), jnp.float32),
                jnp.asarray(rng.randn(b, s, h, d), jnp.float32),
                jnp.asarray((rng.rand(b, s) < 0.9).astype(np.int32)))

    prev_prof = os.environ.get("ALINK_PROFILING")
    try:
        os.environ["ALINK_PROFILING"] = "on"
        sgns = bench_pair("sgns", build_sgns, sgns_args)
        attn = bench_pair("attn", build_attn, attn_args)
        cands = kernel_candidates(top=8)
    finally:
        if prev_prof is None:
            os.environ.pop("ALINK_PROFILING", None)
        else:
            os.environ["ALINK_PROFILING"] = prev_prof

    candidates = [{
        "kernel": c["kernel"],
        "exec_total_s": c["exec_total_s"],
        "bound": c["bound"],
        "efficiency": c["efficiency"],
        "lost_s": c["lost_s"],
        "custom_kernel": c["custom_kernel"],
        "kernel_enabled": c["kernel_enabled"],
    } for c in cands]

    def eff_moved(pair):
        before, after = pair["efficiency_before"], pair["efficiency_after"]
        if before is None or after is None:
            return True   # no roofline capture — nothing to gate on
        return after >= before * 0.95   # toward the ceiling, 5% noise floor

    ok = (sgns["parity_ok"] and attn["parity_ok"]
          and (not wall_gate_applies
               or (eff_moved(sgns) and eff_moved(attn)
                   and sgns["wall_verdict"] in ("no-change", "improvement")
                   and attn["wall_verdict"] in ("no-change", "improvement"))))
    return {
        "platform": platform,
        "interpret_mode": interp,
        "wall_gate_applies": wall_gate_applies,
        "registry": {kid: {"knob": rec["knob"],
                           "enabled": rec["enabled"]}
                     for kid, rec in registry().items()},
        "sgns": sgns,
        "attention": attn,
        "candidates": candidates,
        "gate": {"ok": bool(ok)},
    }


def bench_aps(steps=20):
    """Pod-scale sparse-embedding exchange (parallel/aps.py): owner-routed
    pull/push on the sharded-skipgram exchange pattern — rows/s through a
    full pull→push cycle on the largest mesh, the per-device
    comm-bytes-per-step accounting behind the O(B·D) claim (routed bytes
    stay ~flat as the model axis grows; the legacy all-gather reference
    grows linearly), and a benchstats perf_gate verdict of the routed step
    against the all-gather step on identical inputs."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from alink_tpu.common.benchstats import perf_gate
    from alink_tpu.common.profiling import collective_bytes
    from alink_tpu.parallel.aps import (ShardedEmbedding, model_mesh, pull,
                                        pull_allgather, push, push_allgather)
    from alink_tpu.parallel.mesh import AXIS_MODEL
    from alink_tpu.parallel.shardmap import shard_map

    M = len(jax.devices())
    rows, D, B = 2048, 64, 1024     # per-shard rows / dim / per-device batch

    def build(m, routed, op):
        mesh = model_mesh(m)
        V = rows * m
        rng = np.random.default_rng(0)
        ids = rng.integers(0, V, size=(m, B)).astype(np.int32)
        grads = rng.normal(size=(m, B, D)).astype(np.float32)
        table = ShardedEmbedding(mesh, V, D)
        _pull = pull if routed else pull_allgather
        _push = push if routed else push_allgather

        def body(tl, i, g):
            if op in ("pull", "cycle"):
                v = _pull(tl, i[0], AXIS_MODEL, rows)
                if op == "pull":
                    return v
            g_eff = g[0] + v if op == "cycle" else g[0]
            return _push(tl, i[0], g_eff, AXIS_MODEL, rows, 1e-3)

        f = jax.jit(shard_map(body, mesh=mesh, in_specs=(P(AXIS_MODEL),) * 3,
                              out_specs=P(AXIS_MODEL), check_vma=False))
        args = (table.array,
                jax.device_put(ids, NamedSharding(mesh, P(AXIS_MODEL))),
                jax.device_put(grads, NamedSharding(mesh, P(AXIS_MODEL))))
        return f, args

    # -- throughput: routed pull→push cycle on the full mesh ---------------
    f, args = build(M, True, "cycle")
    f(*args).block_until_ready()                       # compile outside
    t0 = time.perf_counter()
    for _ in range(steps):
        out = f(*args)
    out.block_until_ready()
    rows_per_s = M * B * steps / (time.perf_counter() - t0)

    # -- per-device comm bytes per step, M=1 vs the full mesh --------------
    m_values = sorted({1, min(2, M), M})
    comm = {}
    for op in ("pull", "push"):
        for m in m_values:
            rf, ra = build(m, True, op)
            comm[f"{op}_routed_bytes_m{m}"] = collective_bytes(
                rf.lower(*ra).compile())
        gf, ga = build(M, False, op)
        comm[f"{op}_gather_bytes_m{M}"] = collective_bytes(
            gf.lower(*ga).compile())

    # fractional growth of routed bytes from the smallest multi-device mesh
    # to the full mesh: ~0 when per-device comm is O(B·D); an O(M·B·D)
    # regression reads ~(M/2 - 1). Named *_overhead so the round-over-round
    # bench gate treats lower-as-better and flags growth.
    m_small = min((m for m in m_values if m >= 2), default=M)
    scaling = {}
    for op in ("pull", "push"):
        small = comm[f"{op}_routed_bytes_m{m_small}"]
        big = comm[f"{op}_routed_bytes_m{M}"]
        scaling[f"{op}_comm_scaling_overhead"] = (
            round(big / small - 1.0, 4) if small else 0.0)

    # -- routed vs all-gather wall time on identical inputs ----------------
    gf, ga = build(M, False, "cycle")
    gf(*ga).block_until_ready()
    gate = perf_gate(lambda: gf(*ga).block_until_ready(),
                     lambda: f(*args).block_until_ready(), repeats=7)

    return {
        "model_axis": M,
        "rows_per_s": round(rows_per_s, 1),
        "batch_per_device": B,
        "dim": D,
        **comm,
        **scaling,
        "routed_vs_gather_wall_verdict": gate["verdict"],
        "routed_vs_gather_wall_delta_pct": gate["delta_pct"],
    }


def bench_huge(epochs=2):
    """Huge-embedding family end-to-end through the routed APS + hot-key
    cache (operator/batch/huge.py → embedding/skipgram.py →
    parallel/aps.py): deepwalk-embedding training rows/s on the sharded
    engine, per-device comm-bytes-per-step accounting for
    routed+cache vs routed vs the host all-gather reference (weak scaling:
    rows-per-shard constant, vocab grows with M), the measured hot-key
    cache traffic reduction + hit rate on a Zipf workload, and a benchstats
    perf_gate of the cached step against the uncached routed step."""
    import jax

    from alink_tpu.common.benchstats import perf_gate
    from alink_tpu.common.metrics import metrics
    from alink_tpu.embedding import (SkipGramConfig, build_vocab, make_pairs,
                                     train_skipgram_sharded)
    from alink_tpu.embedding.walks import build_csr, random_walks

    M = len(jax.devices())

    # -- the real workload: deepwalk corpus on a Zipf-degree graph ---------
    rng = np.random.default_rng(0)
    n_nodes, n_edges = 1024, 4096
    src = rng.integers(0, n_nodes, n_edges)
    dst = np.minimum(rng.zipf(1.5, n_edges) - 1, n_nodes - 1)
    indptr, indices, w = build_csr(src, dst, num_nodes=n_nodes)
    walks = random_walks(indptr, indices, w, num_walks=1, walk_length=10,
                         seed=1)
    docs = [[str(v) for v in row] for row in walks]
    vocab, counts = build_vocab(docs)
    cfg = SkipGramConfig(dim=64, window=3, negatives=4, epochs=epochs,
                         batch_size=256, seed=0)
    pairs = make_pairs(docs, vocab, counts, cfg.window, 0.0, cfg.seed)
    pairs = pairs[:20_000]    # cap the drill so the extra stays minutes-fast
    V = len(vocab)
    hot = 256

    def run(hot_rows):
        return train_skipgram_sharded(pairs, V, counts, cfg,
                                      hot_rows=hot_rows).to_numpy()

    # first calls compile (ProgramCache); the timed calls are pure runs
    h0, m0 = (metrics.counter("aps.cache_hits"),
              metrics.counter("aps.cache_misses"))
    emb_cached = run(hot)
    hits = metrics.counter("aps.cache_hits") - h0
    misses = metrics.counter("aps.cache_misses") - m0
    hit_rate = hits / max(1, hits + misses)
    emb_routed = run(0)
    bit_parity = bool(np.array_equal(emb_cached, emb_routed))

    used = (pairs.shape[0] // (cfg.batch_size * M)) * cfg.batch_size * M
    t0 = time.perf_counter()
    run(hot)
    rows_per_s = used * cfg.epochs / (time.perf_counter() - t0)

    gate = perf_gate(lambda: run(0), lambda: run(hot), repeats=3)
    # the cache optimizes WIRE BYTES (the TPU ICI cost, gated via the HLO
    # accounting below); a CPU mesh's collectives are shared-memory copies
    # — latency-bound, bytes are ~free — so the wall verdict there reads
    # the cache's fixed per-step overhead with none of its benefit. Gate
    # wall only on accelerator backends (the platform-aware-compare
    # convention from docs/bench_schema.md), advisory elsewhere.
    platform = jax.devices()[0].platform
    wall_gate_applies = platform in ("tpu", "gpu")

    # -- comm-bytes accounting: the canonical weak-scaling probe (shared
    # with tests/test_weak_scaling.py so the CI pin and this bench always
    # measure the same compiled program)
    from alink_tpu.embedding.engine import collective_bytes_probe

    m_values = sorted({1, min(2, M), M})
    comm = {}
    for m in m_values:
        comm[f"routed_bytes_m{m}"] = collective_bytes_probe(m, "sharded")
        if m >= 2:
            comm[f"cached_bytes_m{m}"] = collective_bytes_probe(
                m, "sharded", hot_rows=16)
            comm[f"gather_bytes_m{m}"] = collective_bytes_probe(m, "host")

    # fractional growth from the smallest multi-device mesh to the full
    # mesh, named *_overhead so the round-over-round gate flags growth
    m_small = min((m for m in m_values if m >= 2), default=M)
    scaling = {}
    for kind in ("routed", "cached"):
        small = comm.get(f"{kind}_bytes_m{m_small}")
        big = comm.get(f"{kind}_bytes_m{M}")
        scaling[f"{kind}_comm_scaling_overhead"] = (
            round(big / small - 1.0, 4) if small and big else 0.0)
    cache_reduction = (1.0 - comm[f"cached_bytes_m{M}"]
                       / comm[f"routed_bytes_m{M}"]) \
        if comm.get(f"routed_bytes_m{M}") else 0.0

    # on a single-device environment every comm verdict is vacuous (zero
    # collective traffic either way) — gate on what is measurable there
    ok = (hit_rate > 0 and bit_parity
          and (not wall_gate_applies
               or gate["verdict"] in ("no-change", "improvement"))
          and (M < 2 or cache_reduction > 0))
    return {
        "model_axis": M,
        "platform": platform,
        "comm_verdicts_vacuous_single_device": M < 2,
        "vocab": V,
        "pairs": int(pairs.shape[0]),
        "deepwalk_rows_per_s": round(rows_per_s, 1),
        "cache_hot_rows": hot,
        "cache_hit_rate": round(hit_rate, 4),
        "cache_bit_parity_vs_routed": bit_parity,
        "cache_traffic_reduction_pct": round(100 * cache_reduction, 2),
        **comm,
        **scaling,
        "cached_vs_routed_wall_verdict": gate["verdict"],
        "cached_vs_routed_wall_delta_pct": gate["delta_pct"],
        "wall_gate_applies": wall_gate_applies,
        "gate": {"ok": bool(ok)},
    }


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(
        description="alink_tpu benchmark driver / BENCH regression gate")
    ap.add_argument(
        "--compare", nargs=2, metavar=("OLD", "NEW"),
        help="compare two BENCH round json files (raw driver output or the "
             "archived {parsed: ...} wrapper) and print the regression "
             "report; exit code 1 when a significant regression is found")
    ap.add_argument(
        "--threshold", type=float, default=None,
        help="override every per-metric noise threshold "
             "(fraction, e.g. 0.1 = 10%%)")
    ap.add_argument(
        "--only", default=None, metavar="NAME[,NAME...]",
        help="run only the named extras (e.g. 'coldstart' or "
             "'compile,serving') and skip the primary BERT metric; prints "
             "the same JSON shape with metric=extras_subset. Unlike the "
             "full run (where a failing extra never sinks the primary "
             "metric), this mode IS the gate: exit 1 when any selected "
             "extra errors or reports gate.ok=false, 2 on unknown names")
    ap.add_argument(
        "--trace-artifact", default=None, metavar="PATH",
        help="after the run, write the span ring as a Perfetto-loadable "
             "chrome://tracing JSON to PATH (open at ui.perfetto.dev) — "
             "the measured span waterfall feeding the kernel-candidates "
             "ranking; drop it next to BENCH_r0N.json per round")
    args = ap.parse_args(argv)
    if args.compare:
        from alink_tpu.common.benchstats import compare_bench_files

        report = compare_bench_files(args.compare[0], args.compare[1],
                                     threshold=args.threshold)
        print(json.dumps(report, indent=2))
        return 1 if report["regressions"] else 0

    bench_fns = (
        ("kmeans_iris", bench_kmeans_iris),
        ("softmax_mnist", bench_softmax_mnist),
        ("gbdt_train", bench_gbdt),
        ("torch_stream_predict", bench_torch_stream),
        ("resnet50_predict", bench_resnet50),
        ("resnet50_savedmodel", bench_resnet50_savedmodel),
        ("bert_text_quality", bench_bert_quality),
        ("executor", bench_executor),
        ("resilience", bench_resilience),
        ("recovery", bench_recovery),
        ("elastic", bench_elastic),
        ("modelstream", bench_modelstream),
        ("compile", bench_compile),
        ("coldstart", bench_coldstart),
        ("observability", bench_observability),
        ("profiling", bench_profiling),
        ("kernels", bench_kernels),
        ("serving", bench_serving),
        ("fleet", bench_fleet),
        ("aps", bench_aps),
        ("huge", bench_huge),
        # LAST on purpose: train_scale compiles its own program family, and
        # running it before the `compile` extra would inflate that extra's
        # cumulative program_cache.compile_s reading vs earlier rounds
        ("train_scale", bench_train_scale),
    )
    only = {n.strip() for n in args.only.split(",")} if args.only else None
    if only is not None:
        known = {n for n, _ in bench_fns}
        unknown = sorted(only - known)
        if unknown:
            # a typoed gate must fail loudly, not pass having run nothing
            print(f"unknown extras {unknown}; known: {sorted(known)}",
                  file=sys.stderr)
            return 2
    extras = {}
    for name, fn in bench_fns:
        if only is not None and name not in only:
            continue
        try:
            extras[name] = fn()
        except Exception as e:  # a failing extra must not sink the primary
            extras[name] = {"error": f"{type(e).__name__}: {e}"[:300]}

    if args.trace_artifact:
        # stderr so stdout stays the parseable BENCH JSON
        try:
            from alink_tpu.common.tracing import write_chrome_trace

            n = write_chrome_trace(args.trace_artifact)
            print(f"trace artifact: {args.trace_artifact} ({n} spans)",
                  file=sys.stderr)
        except Exception as e:
            print(f"trace artifact failed: {e}", file=sys.stderr)

    if only is not None:
        print(json.dumps({"metric": "extras_subset", "value": None,
                          "unit": None, "vs_baseline": None,
                          "extras": extras}))
        failed = any(
            isinstance(v, dict)
            and ("error" in v
                 or (isinstance(v.get("gate"), dict)
                     and not v["gate"].get("ok", True)))
            for v in extras.values())
        return 1 if failed else 0

    per_chip, mfu = bench_bert()
    extras["bert_mfu"] = mfu
    print(json.dumps({
        "metric": "bert_base_finetune_throughput_per_chip",
        "value": round(per_chip, 1),
        "unit": "samples/sec/chip (seq128, bs32, bf16)",
        "vs_baseline": round(per_chip / A100_BERT_BASE_SAMPLES_PER_SEC, 3),
        "extras": extras,
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""Stream variants of the mapper-backed batch operators, generated from the
batch registry.

Capability parity with the reference's stream op column (reference: most of
the ~190 ops under operator/stream/ are thin wrappers binding the SAME
Mapper/ModelMapper used by the batch twin — e.g.
operator/stream/dataproc/ImputerPredictStreamOp.java,
operator/stream/nlp/SegmentStreamOp.java,
operator/stream/classification/LogisticRegressionPredictStreamOp.java).

Python-first collapse: instead of hand-writing each wrapper, this module
reflects over the batch registry and emits one StreamOp per mapper-backed
batch op — stateless mappers become MapStreamOp subclasses, model mappers
become ModelMapStreamOp subclasses (with hot-swap support inherited). The
classes are real module-level types (picklable, documented, cataloged).
"""

from __future__ import annotations

import inspect
from typing import Dict, List, Type

from .base import MapStreamOp, ModelMapStreamOp

__all__: List[str] = []


def _generate() -> Dict[str, type]:
    from ..batch.utils import MapBatchOp, ModelMapBatchOp
    from .. import batch as batch_mod

    out: Dict[str, type] = {}
    for name in dir(batch_mod):
        cls = getattr(batch_mod, name)
        if not inspect.isclass(cls) or not name.endswith("BatchOp"):
            continue
        mapper_cls = getattr(cls, "mapper_cls", None)
        if mapper_cls is None:
            continue
        stream_name = name[: -len("BatchOp")] + "StreamOp"
        if issubclass(cls, ModelMapBatchOp):
            base = ModelMapStreamOp
        elif issubclass(cls, MapBatchOp):
            base = MapStreamOp
        else:
            continue
        attrs = {
            "mapper_cls": mapper_cls,
            "__doc__": (f"Stream twin of {name} — same "
                        f"{mapper_cls.__name__} per micro-batch "
                        f"(reference: the corresponding "
                        f"operator/stream/ wrapper)."),
            "__module__": __name__,
        }
        # surface the batch op's own ParamInfo attrs on the stream twin
        for attr, v in vars(cls).items():
            from ...common.params import ParamInfo

            if isinstance(v, ParamInfo):
                attrs[attr] = v
        out[stream_name] = type(stream_name, (base,), attrs)
    return out


for _name, _cls in _generate().items():
    # don't clobber hand-written stream ops (FTRL, foreign-model predict, ...)
    globals().setdefault(_name, _cls)
    __all__.append(_name)

"""Post-training quantization for served models — the opt-in precision
policy behind ``ModelServer.load(..., precision="int8")``.

Policies (``fp32`` is the identity — precision unset leaves every scoring
path byte-identical to the unquantized code):

- ``int8`` — per-channel symmetric int8 weights everywhere. Kernels whose
  hot loop is one plain matmul (linear scoring, the Naive-Bayes factor
  matmuls, the FM linear term) run **static W8A8**: the activation block is
  quantized with a per-tensor scale fixed at load time by a calibration
  pass over real warmup rows, the matmul accumulates in int32, and one
  fused rescale restores f32 scores. Multi-stage kernels (MLP hidden
  layers, the FM pairwise factors, tree leaf values, the BERT encoder
  parameters) run **weight-only**: int8 weights dequantize in-kernel to
  bf16 and the matmuls accumulate in f32.
- ``bf16`` — weights and activations cast to bf16, outputs f32; no
  calibration (there are no fixed ranges to learn).

Never silent: the serving loader refuses a quantized load whose
calibration sample is synthetic or degenerate, and gates every quantized
load behind an accuracy band against the fp32 baseline — a failing gate
falls back to fp32 with a counted reason (``serving.precision_fallback``).

Quantized programs live in the process-wide ProgramCache under their own
``quant.*`` kernel ids, so fp32 and int8 versions of the same model
coexist without evicting or cross-contaminating each other's programs.

The policy travels to mappers as stamped op params (mappers are rebuilt
from op params on every predict, so params are the only durable channel):

- ``inferencePrecision`` — the active policy string,
- ``quantCalib`` — ``{site: activation-absmax}`` fixed by calibration,
- ``quantSite`` — the op's unique site prefix inside the serving plan.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Any, Dict, Optional, Tuple

import numpy as np

from .exceptions import AkIllegalArgumentException, AkIllegalStateException

FP32 = "fp32"
BF16 = "bf16"
INT8 = "int8"
PRECISIONS = (FP32, BF16, INT8)

# op-param keys the serving loader stamps and mappers read
PRECISION_KEY = "inferencePrecision"
CALIB_KEY = "quantCalib"
SITE_KEY = "quantSite"

_QMAX = 127.0  # symmetric int8 range; -128 is never produced


def resolve_policy(precision) -> Optional[str]:
    """Normalize a precision request: None/""/"fp32" -> None (the identity
    policy), "bf16"/"int8" -> themselves; anything else raises."""
    if precision is None or precision == "":
        return None
    p = str(precision).lower()
    if p not in PRECISIONS:
        raise AkIllegalArgumentException(
            f"unknown precision {precision!r}; choose one of {PRECISIONS}")
    return None if p == FP32 else p


def policy_of(params) -> Optional[str]:
    """The stamped policy on a mapper's params, or None when unset — the
    one read every fp32 predict performs (a dict-membership check), so
    knob-off stays byte-identical AND cost-identical."""
    if params is None or not params.contains(PRECISION_KEY):
        return None
    return resolve_policy(params.get(PRECISION_KEY))


def site_of(params, default: str) -> str:
    if params is not None and params.contains(SITE_KEY):
        return str(params.get(SITE_KEY))
    return default


def calib_scale(params, site: str) -> float:
    """The calibrated per-tensor activation scale for ``site`` (absmax /
    127). A quantized kernel asking for a range calibration never fixed is
    a loader bug — refuse loudly instead of computing garbage scores."""
    calib = params.get(CALIB_KEY) if params is not None \
        and params.contains(CALIB_KEY) else None
    absmax = (calib or {}).get(site)
    if absmax is None or not np.isfinite(absmax) or absmax <= 0.0:
        raise AkIllegalStateException(
            f"int8 inference has no calibrated activation range for site "
            f"{site!r} — the load-time calibration pass did not cover it")
    return float(absmax) / _QMAX


# ---------------------------------------------------------------------------
# weight quantization
# ---------------------------------------------------------------------------


def quantize_per_channel(w: np.ndarray,
                         axis: int = -1) -> Tuple[np.ndarray, np.ndarray]:
    """Symmetric per-channel int8 quantization of a weight array along
    ``axis`` (the output-channel axis; a 1-D weight is one channel).
    Returns ``(wq int8, scale f32)`` with ``wq * scale ~= w``; an all-zero
    channel gets scale 1.0 so dequantization is exact."""
    w = np.asarray(w, np.float32)
    if w.ndim == 0 or w.size == 0:
        return w.astype(np.int8), np.ones_like(w, np.float32)
    if w.ndim == 1:
        absmax = float(np.max(np.abs(w)))
        scale = np.float32(absmax / _QMAX if absmax > 0.0 else 1.0)
        wq = np.clip(np.round(w / scale), -_QMAX, _QMAX).astype(np.int8)
        return wq, np.asarray(scale, np.float32)
    reduce_axes = tuple(i for i in range(w.ndim)
                        if i != (axis % w.ndim))
    absmax = np.max(np.abs(w), axis=reduce_axes, keepdims=True)
    scale = np.where(absmax > 0.0, absmax / _QMAX, 1.0).astype(np.float32)
    wq = np.clip(np.round(w / scale), -_QMAX, _QMAX).astype(np.int8)
    return wq, np.squeeze(scale, axis=reduce_axes)


def quantize_last_axis(w: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Symmetric int8 with one scale per leading index (reduce over the
    LAST axis only) — e.g. tree leaf tables ``(T, K, 2^D)`` get scales
    ``(T, K)``. All-zero rows get scale 1.0."""
    w = np.asarray(w, np.float32)
    absmax = np.max(np.abs(w), axis=-1, keepdims=True)
    scale = np.where(absmax > 0.0, absmax / _QMAX, 1.0).astype(np.float32)
    wq = np.clip(np.round(w / scale), -_QMAX, _QMAX).astype(np.int8)
    return wq, np.squeeze(scale, axis=-1)


def dequantize(wq: np.ndarray, scale: np.ndarray,
               axis: int = -1) -> np.ndarray:
    """Host-side inverse of :func:`quantize_per_channel` (tests/tools)."""
    wq = np.asarray(wq, np.float32)
    s = np.asarray(scale, np.float32)
    if wq.ndim >= 2 and s.ndim == 1:
        shape = [1] * wq.ndim
        shape[axis % wq.ndim] = s.shape[0]
        s = s.reshape(shape)
    return wq * s


def quantize_tree(params) -> Tuple[Any, Any]:
    """Weight-only quantization of a pytree of model parameters: every
    float leaf with >= 2 dims (the matmul weights) becomes int8 with a
    per-channel (last-axis) scale; 1-D floats (biases, layernorm gains)
    and integer leaves pass through as-is with scale None. Returns
    ``(q_tree, scale_tree)`` with identical treedefs."""
    import jax

    def q(leaf):
        a = np.asarray(leaf)
        if a.ndim >= 2 and np.issubdtype(a.dtype, np.floating):
            return quantize_per_channel(a, axis=-1)
        return a, None

    pairs = jax.tree_util.tree_map(q, params)
    q_tree = jax.tree_util.tree_map(lambda p: p[0], pairs,
                                    is_leaf=lambda x: isinstance(x, tuple))
    s_tree = jax.tree_util.tree_map(lambda p: p[1], pairs,
                                    is_leaf=lambda x: isinstance(x, tuple))
    return q_tree, s_tree


# ---------------------------------------------------------------------------
# calibration capture
# ---------------------------------------------------------------------------

# Capture is PROCESS-wide, not thread-local: a predict fans out across the
# DAG executor pool (``alink-dag_*`` threads), so the mapper calling
# :func:`observe` is rarely the thread that opened the context. The gate
# lock serializes calibration passes (one model calibrates at a time); the
# record lock guards merges from concurrently-executing mapper blocks.
_capture_gate = threading.Lock()
_capture_lock = threading.Lock()
_capture_rec: Optional[Dict[str, float]] = None


@contextmanager
def calibration(record: Dict[str, float]):
    """Activate activation-range capture for the duration of the context:
    mappers running a predict inside it merge per-site absmax into
    ``record`` — from whatever executor thread the plan schedules them on.
    Outside the context :func:`observe` is a no-op, so production predicts
    pay nothing and change nothing. Calibration passes serialize on a
    process-wide gate; unrelated fp32 traffic served concurrently CAN
    observe into the record, which is why load-time stamping makes sites
    unique per model name."""
    global _capture_rec
    with _capture_gate:
        with _capture_lock:
            _capture_rec = record
        try:
            yield record
        finally:
            with _capture_lock:
                _capture_rec = None


def capturing() -> bool:
    return _capture_rec is not None


def observe(site: str, block) -> None:
    """Record the absmax of one activation block under ``site`` (max-merge
    across calibration batches). Only active inside :func:`calibration`."""
    if _capture_rec is None:
        return
    a = np.asarray(block)
    m = float(np.max(np.abs(a))) if a.size else 0.0
    if not np.isfinite(m):
        m = float("inf")
    with _capture_lock:
        rec = _capture_rec
        if rec is None:
            return
        prev = rec.get(site)
        rec[site] = m if prev is None else max(prev, m)


def degenerate_sites(calib: Dict[str, float]) -> Dict[str, float]:
    """The calibration sites whose recorded range cannot produce a usable
    scale: zero (an all-zero sample quantizes everything to 0) or
    non-finite. An empty dict means the ranges are healthy."""
    return {k: v for k, v in (calib or {}).items()
            if not np.isfinite(v) or v <= 0.0}


# ---------------------------------------------------------------------------
# quantized kernel builders (cached_jit; distinct `quant.*` kernel ids so
# fp32 and int8 programs coexist in the ProgramCache)
# ---------------------------------------------------------------------------


def _quantize_act(jnp, X, sx):
    return jnp.clip(jnp.round(X / sx), -_QMAX, _QMAX).astype(jnp.int8)


def _int8_matmul(jax, jnp, Xq, wq):
    # int8 x int8 -> int32 accumulate; one dot_general for 1-D and 2-D w
    return jax.lax.dot_general(
        Xq, wq, dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)


def _build_int8_linear_score():
    """Static-W8A8 twin of ``linear.score`` (``X @ w + b``): activations
    quantized with the calibrated per-tensor scale, int8 matmul with int32
    accumulation, one fused rescale back to f32."""
    import jax
    import jax.numpy as jnp

    def run(X, wq, b, sw, sx):
        acc = _int8_matmul(jax, jnp, _quantize_act(jnp, X, sx), wq)
        return acc.astype(jnp.float32) * (sx * sw) + b

    return jax.jit(run)


def int8_linear_program():
    from .jitcache import cached_jit

    return cached_jit("quant.linear_score.int8", _build_int8_linear_score)


def _build_int8_nb_score(mtype: str):
    """Static-W8A8 twin of ``naivebayes.score``: each factor matmul runs
    int8 x int8 -> int32 with its own calibrated activation scale (the
    Gaussian form feeds two distinct activations, X² and X)."""
    import jax
    import jax.numpy as jnp

    if mtype == "GAUSSIAN":
        def score(X, aq, bq, c, sa, sb, sxx, sx):
            Xsq = X * X
            t1 = _int8_matmul(jax, jnp, _quantize_act(jnp, Xsq, sxx), aq)
            t2 = _int8_matmul(jax, jnp, _quantize_act(jnp, X, sx), bq)
            return (-(t1.astype(jnp.float32)) * (sxx * sa)
                    + t2.astype(jnp.float32) * (sx * sb) + c)
    elif mtype == "MULTINOMIAL":
        def score(X, aq, bq, c, sa, sb, sxx, sx):
            t = _int8_matmul(jax, jnp, _quantize_act(jnp, X, sx), aq)
            return t.astype(jnp.float32) * (sx * sa) + c
    else:  # BERNOULLI — the binarized block is exactly representable
        def score(X, aq, bq, c, sa, sb, sxx, sx):
            Xb = (X > 0).astype(jnp.int8)
            t = _int8_matmul(jax, jnp, Xb, aq)
            return t.astype(jnp.float32) * sa + c

    return jax.jit(score)


def int8_nb_program(mtype: str):
    from .jitcache import cached_jit

    return cached_jit("quant.naivebayes_score.int8", _build_int8_nb_score,
                      mtype)


def _build_int8_fm_score():
    """FM scoring under int8: the linear term runs static W8A8; the
    pairwise term dequantizes the factor matrix V to bf16 in-kernel
    (weight-only — V feeds squares and cross terms, not one matmul)."""
    import jax
    import jax.numpy as jnp

    from ..optim import fm_pairwise

    def run(X, w0, wq, Vq, sw, sv, sx):
        lin = _int8_matmul(jax, jnp, _quantize_act(jnp, X, sx), wq)
        V = (Vq.astype(jnp.bfloat16)
             * sv.astype(jnp.bfloat16)[None, :])
        pair = fm_pairwise(X.astype(jnp.bfloat16), V)
        return (w0[0] + lin.astype(jnp.float32) * (sx * sw)
                + pair.astype(jnp.float32))

    return jax.jit(run)


def int8_fm_program():
    from .jitcache import cached_jit

    return cached_jit("quant.fm_score.int8", _build_int8_fm_score)


def _build_int8_mlp_score(sizes: tuple):
    """Weight-only int8 MLP forward: each layer's weight matrix
    dequantizes to bf16 in-kernel, activations run bf16, accumulation and
    the sigmoid run f32 (layer inputs are data-dependent, so static
    activation scales would need per-layer calibration depth this runtime
    does not assume)."""
    import jax
    import jax.numpy as jnp

    n_layers = len(sizes) - 1

    def run(X, *packed):
        h = X.astype(jnp.bfloat16)
        for i in range(n_layers):
            Wq, s, b = packed[3 * i], packed[3 * i + 1], packed[3 * i + 2]
            W = Wq.astype(jnp.bfloat16) * s.astype(jnp.bfloat16)[None, :]
            h = jnp.dot(h, W, preferred_element_type=jnp.float32) + b
            if i < n_layers - 1:
                h = jax.nn.sigmoid(h).astype(jnp.bfloat16)
        return h.astype(jnp.float32)

    return jax.jit(run)


def int8_mlp_program(sizes: tuple):
    from .jitcache import cached_jit

    return cached_jit("quant.mlp_score.int8", _build_int8_mlp_score,
                      tuple(int(s) for s in sizes))


def bf16_round(a: np.ndarray) -> np.ndarray:
    """The ``bf16`` policy's numerics: round a block through bfloat16 and
    hand it back as f32. TPU bf16 matmuls accumulate in f32, so rounding
    the inputs and computing in the already-warmed f32 programs reproduces
    the bf16 result without tracing a single new program — the policy
    changes values, never shapes or dtypes on the wire."""
    import ml_dtypes

    return np.asarray(a, np.float32).astype(
        ml_dtypes.bfloat16).astype(np.float32)


def _build_int8_tree_predict(depth: int):
    """Weight-only int8 twin of ``tree.predict``: leaf values dequantize
    in-kernel (per-tree per-output-dim scales); features and thresholds
    stay f32 so split routing — and therefore the traversal path — is
    bit-identical to the fp32 ensemble."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def run(X, feats, thrs, leaves_q, lscale, base_score):
        n = X.shape[0]

        def one_tree(f, t, lq, ls):
            node = jnp.zeros(n, jnp.int32)
            pos = jnp.zeros(n, jnp.int32)
            for _ in range(depth):
                fs = f[pos]
                ts = t[pos]
                safe = jnp.maximum(fs, 0)
                x = jnp.take_along_axis(X, safe[:, None], 1)[:, 0]
                left = (fs < 0) | (x <= ts)
                node = node * 2 + (1 - left.astype(jnp.int32))
                pos = 2 * pos + 1 + (1 - left.astype(jnp.int32))
            lv = lq.astype(jnp.float32) * ls[:, None]
            return lv[:, node]  # (K, n)

        scores = jax.vmap(one_tree)(feats, thrs, leaves_q, lscale)
        return scores.sum(0).T + base_score[None, :]

    return run


def int8_tree_program(depth: int):
    from .jitcache import cached_jit

    return cached_jit("quant.tree_predict.int8", _build_int8_tree_predict,
                      int(depth))


# ---------------------------------------------------------------------------
# accuracy-band gate
# ---------------------------------------------------------------------------


def _is_jsonish(v) -> bool:
    return isinstance(v, str) and v[:1] in ("{", "[")


def accuracy_band_report(base_rows, cand_rows, out_types,
                         *, band: float, tol: float) -> Dict[str, Any]:
    """Compare a quantized predict against its fp32 baseline over the
    calibration rows. Label-like (non-float) columns gate on agreement
    (disagreement fraction <= ``band``); numeric columns gate on relative
    deviation (max |Δ| / max(1, |base|) <= ``tol``). JSON-detail string
    columns are skipped — their low-order probability digits legitimately
    move under quantization and are not the serving contract. Returns
    ``{"ok", "agreement", "max_rel_diff", "band", "tol", "rows"}``."""
    from .mtable import AlinkTypes

    n = len(base_rows)
    agree_num = agree_den = 0
    max_rel = 0.0
    for bi, ci in zip(base_rows, cand_rows):
        for col, (bv, cv) in enumerate(zip(bi, ci)):
            tp = out_types[col] if col < len(out_types) else None
            numeric = tp in (AlinkTypes.DOUBLE, AlinkTypes.FLOAT) or (
                isinstance(bv, float) and not isinstance(bv, bool))
            if numeric and bv is not None and cv is not None:
                b = float(bv)
                c = float(cv)
                max_rel = max(max_rel, abs(b - c) / max(1.0, abs(b)))
                continue
            if _is_jsonish(bv):
                continue
            agree_den += 1
            try:
                agree_num += int(bool(bv == cv))
            except Exception:  # exotic cells (vectors/tensors)
                agree_num += int(str(bv) == str(cv))
    agreement = agree_num / agree_den if agree_den else 1.0
    ok = agreement >= 1.0 - band and max_rel <= tol
    return {"ok": bool(ok), "agreement": round(agreement, 6),
            "max_rel_diff": round(max_rel, 8), "band": band, "tol": tol,
            "rows": n}

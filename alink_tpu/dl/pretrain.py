"""Masked-LM pretraining for the BERT stack.

Capability parity with the reference's pretrain-then-finetune story: its
BERT ops consume checkpoints produced by upstream MLM pretraining
(reference: core/src/main/java/com/alibaba/alink/common/dl/
BaseEasyTransferTrainBatchOp.java + BertResources.java — the ops download
google-research checkpoints; pretraining itself lives outside the Java
code). Here pretraining is in-framework: one jitted MLM step over the
TransformerEncoder, BERT's 80/10/10 masking, and a tied-embedding output
head (logits = states @ tok_emb.T, the original BERT weight tying) — so a
user can produce, save (HF layout via ``save_bert_checkpoint``) and re-ingest
domain checkpoints without leaving the framework."""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from .modules import BertConfig, TransformerEncoder
from .tokenizer import MASK, Tokenizer


def _mask_tokens(ids: np.ndarray, attn: np.ndarray, mask_id: int,
                 vocab_size: int, rng: np.random.Generator,
                 mask_prob: float, n_specials: int = 5):
    """BERT masking: select ``mask_prob`` of real tokens; 80% -> [MASK],
    10% -> random token, 10% -> kept. Returns (masked_ids, target_mask)."""
    sel = (rng.random(ids.shape) < mask_prob) & (attn == 1) \
        & (ids >= n_specials)
    masked = ids.copy()
    r = rng.random(ids.shape)
    masked[sel & (r < 0.8)] = mask_id
    rand_sel = sel & (r >= 0.8) & (r < 0.9)
    masked[rand_sel] = rng.integers(
        n_specials, vocab_size, size=int(rand_sel.sum()))
    return masked, sel


def pretrain_mlm(
    texts: Sequence[str],
    *,
    vocab_size: int = 2000,
    hidden_size: int = 128,
    num_layers: int = 2,
    num_heads: int = 4,
    intermediate_size: int = 256,
    max_len: int = 48,
    epochs: int = 30,
    batch_size: int = 64,
    learning_rate: float = 3e-4,
    mask_prob: float = 0.15,
    seed: int = 0,
    tokenizer: Optional[Tokenizer] = None,
) -> Tuple[BertConfig, dict, Tokenizer, List[float]]:
    """MLM-pretrain a tiny BERT on raw texts. Returns
    ``(cfg, params, tokenizer, loss_history)`` — params fit
    ``save_bert_checkpoint`` and the fine-tune ``checkpointFilePath`` path.
    """
    import jax
    import jax.numpy as jnp
    import optax

    tok = tokenizer or Tokenizer.build(list(texts), vocab_size=vocab_size)
    cfg = BertConfig(
        vocab_size=tok.vocab_size, hidden_size=hidden_size,
        num_layers=num_layers, num_heads=num_heads,
        intermediate_size=intermediate_size, max_position=max_len,
        dropout=0.0, pool="cls")
    model = TransformerEncoder(cfg)

    enc = tok.encode_batch([str(t) for t in texts], max_len=max_len)
    ids = np.asarray(enc["input_ids"], np.int32)
    attn = np.asarray(enc["attention_mask"], np.int32)
    mask_id = tok.vocab[MASK]

    params = model.init(jax.random.PRNGKey(seed), ids[:1], attn[:1])
    tx = optax.adamw(learning_rate, weight_decay=0.01)
    opt_state = tx.init(params["params"])

    @jax.jit
    def step(params, opt_state, masked, attn, targets, sel):
        def loss(p):
            states = model.apply({"params": p["params"]}, masked, attn,
                                 return_sequence=True)
            emb = p["params"]["tok_emb"]["embedding"].astype(jnp.float32)
            logits = states @ emb.T  # tied-embedding MLM head
            ll = optax.softmax_cross_entropy_with_integer_labels(
                logits, targets)
            w = sel.astype(jnp.float32)
            return (ll * w).sum() / jnp.maximum(w.sum(), 1.0)

        l, g = jax.value_and_grad(loss)(params)
        updates, opt_state2 = tx.update(g["params"], opt_state,
                                        params["params"])
        new_p = optax.apply_updates(params["params"], updates)
        return {"params": new_p}, opt_state2, l

    rng = np.random.default_rng(seed)
    n = ids.shape[0]
    history: List[float] = []
    for ep in range(epochs):
        order = rng.permutation(n)
        ep_losses = []
        for s in range(0, n, batch_size):
            idx = order[s:s + batch_size]
            masked, sel = _mask_tokens(
                ids[idx], attn[idx], mask_id, tok.vocab_size, rng, mask_prob)
            params, opt_state, l = step(
                params, opt_state, masked, attn[idx], ids[idx], sel)
            ep_losses.append(float(l))
        history.append(float(np.mean(ep_losses)))
    return cfg, jax.device_get(params), tok, history


def pretrain_and_save(texts: Sequence[str], out_dir: str, **kw) -> dict:
    """Pretrain + write the HF-layout checkpoint dir consumed by
    ``checkpointFilePath`` on the BERT ops. Returns a summary dict."""
    from .pretrained import save_bert_checkpoint

    cfg, params, tok, history = pretrain_mlm(texts, **kw)
    save_bert_checkpoint(params, cfg, out_dir, tok.to_list())
    return {
        "path": out_dir,
        "vocab_size": tok.vocab_size,
        "initial_loss": round(history[0], 4),
        "final_loss": round(history[-1], 4),
        "epochs": len(history),
    }

"""Structured step metrics + profiling hooks.

The reference has almost no tracing (SURVEY §5: slf4j logs + a JUnit
stopwatch; reference: common/AlinkGlobalConfiguration.java:21-27
isPrintProcessInfo gate). The TPU build leans on ``jax.profiler`` and a
structured in-process metrics recorder instead — SURVEY told the build to
do this "from day one".

Usage:
    from alink_tpu.common.metrics import metrics, timed, profile_trace

    with timed("gbdt.train"):
        ...
    metrics.record("bert.step", step=i, loss=l, samples_per_sec=sps)
    with profile_trace("/tmp/trace"):   # Perfetto trace via jax.profiler
        train()
    metrics.summary()                   # {'gbdt.train': {...}, ...}
"""

from __future__ import annotations

import contextlib
import json
import logging
import threading
import time
from collections import defaultdict
from typing import Any, Dict, List, Optional

logger = logging.getLogger("alink_tpu.metrics")


class StepMetrics:
    """In-process metric streams: named series of {step, **values} dicts plus
    aggregated timers and monotonic counters. One global instance
    (``metrics``) serves the whole session; algorithms record cheaply,
    callers read ``series``/``counters``/``summary``."""

    def __init__(self):
        self._series: Dict[str, List[Dict[str, Any]]] = defaultdict(list)
        self._timers: Dict[str, List[float]] = defaultdict(list)
        self._counters: Dict[str, int] = defaultdict(int)
        self._counter_lock = threading.Lock()
        self.enabled = True

    def record(self, name: str, **values):
        if self.enabled:
            self._series[name].append(dict(values))

    def record_bounded(self, name: str, limit: int, **values):
        """record() with a ring bound — high-frequency series (the executor
        emits per-node records on every collect/execute) must not grow
        without bound in long-lived serving processes."""
        if self.enabled:
            s = self._series[name]
            s.append(dict(values))
            if len(s) > limit:
                del s[: len(s) - limit]

    def add_time(self, name: str, seconds: float):
        if self.enabled:
            self._timers[name].append(seconds)

    def incr(self, name: str, n: int = 1):
        """Monotonic event counter (retries, dead-letter drops, defusions).
        Counters count even while recording is disabled — they are the
        signal that something went wrong, which is exactly when a metrics
        blackout must not hide it."""
        with self._counter_lock:
            self._counters[name] += n

    def counter(self, name: str) -> int:
        with self._counter_lock:
            return self._counters.get(name, 0)

    def counters(self, prefix: str = "") -> Dict[str, int]:
        with self._counter_lock:
            return {k: v for k, v in self._counters.items()
                    if k.startswith(prefix)}

    def series(self, name: str) -> List[Dict[str, Any]]:
        return list(self._series.get(name, []))

    def last(self, name: str) -> Optional[Dict[str, Any]]:
        s = self._series.get(name)
        return dict(s[-1]) if s else None

    def timer_stats(self, name: str) -> Optional[Dict[str, float]]:
        ts = self._timers.get(name)
        if not ts:
            return None
        return {"count": len(ts), "total_s": sum(ts),
                "mean_s": sum(ts) / len(ts), "max_s": max(ts)}

    def summary(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for name in self._timers:
            out[name] = self.timer_stats(name)
        for name, s in self._series.items():
            out.setdefault(name, {})
            out[name] = {**(out[name] or {}), "points": len(s),
                         "last": s[-1] if s else None}
        for name, v in self.counters().items():
            out.setdefault(name, {})
            out[name] = {**(out[name] or {}), "count": v}
        return out

    def to_json(self) -> str:
        return json.dumps(self.summary(), default=str)

    def reset(self):
        self._series.clear()
        self._timers.clear()
        with self._counter_lock:
            self._counters.clear()


metrics = StepMetrics()


# ---------------------------------------------------------------------------
# Executor node-phase accounting
# ---------------------------------------------------------------------------
# The DAG executor opens a per-node context on the thread running the node;
# lower layers (device streaming, staging) add transfer/compute seconds into
# whatever node is active without knowing about the executor. No-op when no
# node context is open (direct op calls, tests).

_node_ctx = threading.local()


@contextlib.contextmanager
def node_phase_context(phases: Dict[str, float]):
    prev = getattr(_node_ctx, "phases", None)
    _node_ctx.phases = phases
    try:
        yield phases
    finally:
        _node_ctx.phases = prev


def add_node_phase(key: str, seconds: float):
    phases = getattr(_node_ctx, "phases", None)
    if phases is not None:
        phases[key] = phases.get(key, 0.0) + seconds


def executor_trace() -> List[Dict[str, Any]]:
    """Per-node records of the last executed DAGs: one dict per node with
    ``op``/``wall_s`` plus any phases (``transfer_s``, ``compute_s``,
    ``fused``) the node reported. Feeds the BENCH ``executor`` extra."""
    return metrics.series("executor.node")


def executor_phase_summary() -> Dict[str, Any]:
    """Aggregate the executor trace per op class: count, total wall, and the
    transfer/compute split where nodes reported one."""
    out: Dict[str, Dict[str, float]] = {}
    for rec in executor_trace():
        d = out.setdefault(rec.get("op", "?"),
                           {"count": 0, "wall_s": 0.0})
        d["count"] += 1
        d["wall_s"] = round(d["wall_s"] + rec.get("wall_s", 0.0), 6)
        for k in ("transfer_s", "compute_s", "compile_s"):
            if k in rec:
                d[k] = round(d.get(k, 0.0) + rec[k], 6)
    return out


@contextlib.contextmanager
def timed(name: str, recorder: Optional[StepMetrics] = None):
    """Wall-clock timer context; feeds the global recorder by default."""
    rec = recorder or metrics
    t0 = time.perf_counter()
    try:
        yield
    finally:
        rec.add_time(name, time.perf_counter() - t0)


_drop_logged = False


def _count_drop(where: str, exc: BaseException):
    """A failure inside the metrics/profiling machinery itself must not
    abort the measured code — but it must not vanish either: count it in
    ``metrics.dropped`` and log the first occurrence at debug."""
    global _drop_logged
    metrics.incr("metrics.dropped")
    if not _drop_logged:
        _drop_logged = True
        logger.debug("metrics drop at %s: %r (further drops counted in "
                     "the 'metrics.dropped' counter only)", where, exc)


@contextlib.contextmanager
def profile_trace(log_dir: str, *, host_tracer_level: int = 2):
    """``jax.profiler`` trace context (Perfetto/TensorBoard viewable). No-op
    fallback if the profiler cannot start (e.g. twice in one process);
    start/stop failures are counted in ``metrics.dropped``, never raised."""
    import jax

    started = False
    try:
        jax.profiler.start_trace(log_dir)
        started = True
    except Exception as e:
        _count_drop("profile_trace.start", e)
    try:
        yield
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception as e:
                _count_drop("profile_trace.stop", e)

"""Streaming runtime + online learning tests (reference model:
pyalink ftrl_demo.ipynb — batch warm-start -> FTRL train stream -> hot-swap
predict -> model filter -> windowed eval)."""

import numpy as np

from alink_tpu.common.mtable import MTable
from alink_tpu.operator.batch.base import TableSourceBatchOp
from alink_tpu.operator.batch import LogisticRegressionTrainBatchOp
from alink_tpu.operator.stream import (
    BinaryClassModelFilterStreamOp,
    EvalBinaryClassStreamOp,
    FtrlPredictStreamOp,
    FtrlTrainStreamOp,
    TableSourceStreamOp,
)


def _lr_table(n=600, seed=0, w=(2.0, -3.0), b=0.5):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, 2).astype(np.float64)
    logits = X @ np.asarray(w) + b
    y = (1 / (1 + np.exp(-logits)) > rng.rand(n)).astype(np.int64)
    return MTable({"f0": X[:, 0], "f1": X[:, 1], "label": y})


def test_stream_source_roundtrip():
    t = _lr_table(100)
    out = TableSourceStreamOp(t, numChunks=7).collect()
    assert out.num_rows == 100
    np.testing.assert_array_equal(out.col("label"), t.col("label"))


def test_ftrl_train_and_predict():
    t = _lr_table(800, seed=1)
    stream = TableSourceStreamOp(t, numChunks=20)
    train = FtrlTrainStreamOp(
        featureCols=["f0", "f1"], labelCol="label", alpha=0.5,
        modelSaveInterval=5,
    ).link_from(stream)
    pred = FtrlPredictStreamOp(
        predictionCol="p", predictionDetailCol="pd"
    ).link_from(train, TableSourceStreamOp(t, numChunks=20))
    out = pred.collect()
    acc = np.mean(
        np.asarray(out.col("p")).astype(str)
        == np.asarray(out.col("label")).astype(str)
    )
    assert acc > 0.8, acc


def test_ftrl_warm_start():
    t = _lr_table(400, seed=2)
    batch_model = LogisticRegressionTrainBatchOp(
        featureCols=["f0", "f1"], labelCol="label",
    ).link_from(TableSourceBatchOp(t)).collect()
    stream = TableSourceStreamOp(t, numChunks=10)
    train = FtrlTrainStreamOp(
        batch_model, featureCols=["f0", "f1"], labelCol="label",
        modelSaveInterval=2,
    ).link_from(stream)
    models = list(train._stream())
    assert len(models) == 5
    # predict with the final snapshot beats chance comfortably
    pred = FtrlPredictStreamOp(predictionCol="p").link_from(
        TableSourceStreamOp(models[-1], numChunks=1),
        TableSourceStreamOp(t, numChunks=4),
    ).collect()
    acc = np.mean(
        np.asarray(pred.col("p")).astype(str)
        == np.asarray(t.col("label")).astype(str)
    )
    assert acc > 0.8, acc


def test_model_filter_and_eval():
    t = _lr_table(600, seed=3)
    train = FtrlTrainStreamOp(
        featureCols=["f0", "f1"], labelCol="label", modelSaveInterval=3,
    ).link_from(TableSourceStreamOp(t, numChunks=15))
    filt = BinaryClassModelFilterStreamOp(
        labelCol="label", accuracyThreshold=0.6,
    ).link_from(train, TableSourceStreamOp(t, numChunks=15))
    models = list(filt._stream())
    assert len(models) >= 1

    pred = FtrlPredictStreamOp(
        predictionCol="p", predictionDetailCol="pd"
    ).link_from(
        FtrlTrainStreamOp(
            featureCols=["f0", "f1"], labelCol="label", modelSaveInterval=3,
        ).link_from(TableSourceStreamOp(t, numChunks=15)),
        TableSourceStreamOp(t, numChunks=15),
    )
    ev = EvalBinaryClassStreamOp(
        labelCol="label", predictionDetailCol="pd", positiveLabelValueString="1",
    ).link_from(pred).collect()
    import json

    rows = [json.loads(v) for v in ev.col("Data")]
    assert rows[-1]["Count"] > 0
    assert 0.0 <= rows[-1]["AUC"] <= 1.0


def test_ftrl_default_feature_cols_persisted():
    """featureCols left unset: training resolves the default numeric columns
    once (label excluded) and persists them in snapshot meta, so predict
    binds to the same columns (advisor round-1 medium finding)."""
    from alink_tpu.common.model import table_to_model

    t = _lr_table(400, seed=4)
    train = FtrlTrainStreamOp(labelCol="label", modelSaveInterval=2).link_from(
        TableSourceStreamOp(t, numChunks=10)
    )
    models = list(train._stream())
    meta, _ = table_to_model(models[-1])
    assert meta["featureCols"] == ["f0", "f1"]
    pred = FtrlPredictStreamOp(predictionCol="p").link_from(
        TableSourceStreamOp(models[-1], numChunks=1),
        TableSourceStreamOp(t, numChunks=4),
    ).collect()
    acc = np.mean(
        np.asarray(pred.col("p")).astype(str)
        == np.asarray(t.col("label")).astype(str)
    )
    assert acc > 0.8, acc


def test_ftrl_single_label_warmup_deferred():
    """Snapshots are held back until both classes are observed — a
    single-label first micro-batch must not freeze a 'None' label."""
    t = _lr_table(200, seed=5)
    order = np.argsort(t.col("label"), kind="stable")  # all 0s first
    t_sorted = t.take(order)
    train = FtrlTrainStreamOp(
        featureCols=["f0", "f1"], labelCol="label", modelSaveInterval=1,
    ).link_from(TableSourceStreamOp(t_sorted, numChunks=10))
    from alink_tpu.common.model import table_to_model

    models = list(train._stream())
    assert models  # some snapshots survive
    for m in models:
        meta, _ = table_to_model(m)
        assert None not in meta["labels"] and len(meta["labels"]) == 2


def test_online_fm_stream():
    import numpy as np

    from alink_tpu.common.mtable import MTable
    from alink_tpu.operator.stream import (OnlineFmPredictStreamOp,
                                           OnlineFmTrainStreamOp,
                                           TableSourceStreamOp)

    rng = np.random.default_rng(0)
    X = rng.normal(size=(600, 4)).astype(np.float64)
    y = ((X[:, 0] * X[:, 1] + X[:, 2]) > 0).astype(np.int64)
    cols = {f"f{i}": X[:, i] for i in range(4)}
    cols["label"] = y
    t = MTable(cols)
    models = OnlineFmTrainStreamOp(
        labelCol="label", featureCols=[f"f{i}" for i in range(4)],
        numFactor=4, learnRate=0.3, modelSaveInterval=1).link_from(
        TableSourceStreamOp(t, chunkSize=100))
    pred = OnlineFmPredictStreamOp(predictionCol="pred").link_from(
        models, TableSourceStreamOp(t, chunkSize=100))
    out = pred.collect()
    acc = float((np.asarray(out.col("pred")) == y).mean())
    assert acc > 0.7


def test_online_learning_refines_batch_model():
    import numpy as np

    from alink_tpu.common.mtable import MTable
    from alink_tpu.operator.batch import (LinearRegTrainBatchOp,
                                          MemSourceBatchOp)
    from alink_tpu.operator.stream import (OnlineLearningStreamOp,
                                           TableSourceStreamOp)

    rng = np.random.default_rng(1)
    # warm start on slope 2 data, stream carries slope 3 data: refinement
    # should move the weight toward 3
    warm_rows = [(float(x), float(2 * x)) for x in rng.normal(size=100)]
    warm = LinearRegTrainBatchOp(featureCols=["x"], labelCol="y").link_from(
        MemSourceBatchOp(warm_rows, "x double, y double")).collect()

    xs = rng.normal(size=2000)
    t = MTable({"x": xs, "y": 3.0 * xs})
    out = OnlineLearningStreamOp(learnRate=0.2, modelSaveInterval=5) \
        .link_from(TableSourceStreamOp(warm, numChunks=1),
                   TableSourceStreamOp(t, chunkSize=100))
    snapshots = list(out._stream())
    assert snapshots
    from alink_tpu.common.model import table_to_model
    _, arrays = table_to_model(snapshots[-1])
    assert abs(float(arrays["weights"][0]) - 3.0) < 0.3


def test_online_fm_label_warmup():
    import numpy as np

    from alink_tpu.common.mtable import MTable
    from alink_tpu.operator.stream import (OnlineFmTrainStreamOp,
                                           TableSourceStreamOp)

    # label-sorted stream: the first chunks carry only label 0
    X = np.random.default_rng(4).normal(size=(200, 2))
    y = np.concatenate([np.zeros(100), np.ones(100)]).astype(np.int64)
    t = MTable({"a": X[:, 0], "b": X[:, 1], "label": y})
    models = list(OnlineFmTrainStreamOp(
        labelCol="label", featureCols=["a", "b"], modelSaveInterval=1)
        .link_from(TableSourceStreamOp(t, chunkSize=40))._stream())
    assert models  # emitted once both labels arrived
    from alink_tpu.common.model import table_to_model
    meta, _ = table_to_model(models[0])
    assert len(meta["labels"]) == 2


def test_eval_outlier_stream_cumulative():
    import numpy as np

    from alink_tpu.common.mtable import MTable
    from alink_tpu.operator.stream import TableSourceStreamOp
    from alink_tpu.operator.stream.outlier import EvalOutlierStreamOp

    # 100 rows: predictions perfect in the first half, wrong in the second
    y = np.asarray([1] * 10 + [0] * 40 + [1] * 10 + [0] * 40)
    pred = np.concatenate([y[:50].astype(bool), ~y[50:].astype(bool)])
    t = MTable({"label": y.astype(np.int64), "pred": pred})
    rows = list(EvalOutlierStreamOp(labelCol="label", predictionCol="pred")
                .link_from(TableSourceStreamOp(t, chunkSize=50))._stream())
    first, last = rows[0], rows[-1]
    assert first.col("F1")[0] == 1.0          # perfect so far
    assert last.col("F1")[0] < 0.5            # cumulative drops
    assert last.col("Count")[0] == 100


def test_csv_stream_source(tmp_path):
    import numpy as np

    from alink_tpu.operator.stream import CsvSourceStreamOp

    p = str(tmp_path / "data.csv")
    with open(p, "w") as f:
        for i in range(10):
            f.write(f"{i},{i * 2.5}\n")
    src = CsvSourceStreamOp(filePath=p, schemaStr="id bigint, v double",
                            chunkSize=4)
    chunks = list(src._stream())
    assert [c.num_rows for c in chunks] == [4, 4, 2]
    assert chunks[0].col("v")[1] == 2.5


def test_summarizer_stream_cumulative():
    import numpy as np

    from alink_tpu.common.mtable import MTable
    from alink_tpu.operator.stream import (SummarizerStreamOp,
                                           TableSourceStreamOp)

    vals = np.arange(100, dtype=np.float64)
    t = MTable({"v": vals})
    rows = list(SummarizerStreamOp().link_from(
        TableSourceStreamOp(t, chunkSize=25))._stream())
    assert len(rows) == 4
    first, last = rows[0], rows[-1]
    assert first.col("count")[0] == 25
    assert last.col("count")[0] == 100
    assert last.col("mean")[0] == 49.5
    assert last.col("max")[0] == 99.0
    assert abs(last.col("variance")[0] - vals.var(ddof=1)) < 1e-9


def test_stream_checkpoint_replay(tmp_path):
    """A crashed stream job resumes from the failure point, not from
    scratch (reference: StreamOperator.setCheckPointConf); at-least-once
    per chunk, no reprocessing of acked chunks."""
    from alink_tpu.common.mtable import MTable
    from alink_tpu.operator.stream import (
        AckCheckpointStreamOp,
        CheckpointedSourceStreamOp,
        StreamCheckpoint,
        TableSourceStreamOp,
    )

    t = MTable.from_rows([(i,) for i in range(10)], "v long")
    state = str(tmp_path / "job.ckpt")
    processed = []

    def run(crash_after=None):
        ck = StreamCheckpoint(state)
        src = CheckpointedSourceStreamOp(
            TableSourceStreamOp(t, chunkSize=2), ck)
        ack = AckCheckpointStreamOp(ck).link_from(src)
        for n, chunk in enumerate(ack._stream()):
            processed.append(tuple(chunk.col("v")))
            if crash_after is not None and n + 1 >= crash_after:
                raise RuntimeError("simulated crash")

    try:
        run(crash_after=2)  # chunk 0 acked; chunk 1 in flight at the crash
    except RuntimeError:
        pass
    assert processed == [(0, 1), (2, 3)]
    run()  # resume: the unacked in-flight chunk replays (at-least-once)
    assert processed == [(0, 1), (2, 3),
                         (2, 3), (4, 5), (6, 7), (8, 9)]
    # a fresh run after completion processes nothing (all acked)
    before = list(processed)
    run()
    assert processed == before
    # reset clears the journal: full replay
    StreamCheckpoint(state).reset()
    n_before = len(processed)
    run()
    assert len(processed) == n_before + 5  # full replay of all 5 chunks


def test_stream_checkpoint_corrupt_journal_degrades_to_full_replay(tmp_path):
    """Regression: a truncated/corrupt journal (exactly what a crash
    leaves behind) must read as "no checkpoint" — never crash the
    restart path — and a stale .tmp from an interrupted ack is cleaned."""
    from alink_tpu.operator.stream import StreamCheckpoint

    state = str(tmp_path / "job.ckpt")
    ck = StreamCheckpoint(state)
    ck.ack(3)
    assert ck.last_acked() == 3

    # truncated mid-write
    with open(state, "w") as f:
        f.write('{"last_ack')
    assert StreamCheckpoint(state).last_acked() == -1
    # wrong type in a structurally valid journal
    with open(state, "w") as f:
        f.write('{"last_acked": "not-a-number"}')
    assert StreamCheckpoint(state).last_acked() == -1
    with open(state, "w") as f:
        f.write('{"last_acked": null}')
    assert StreamCheckpoint(state).last_acked() == -1
    # binary garbage
    with open(state, "wb") as f:
        f.write(b"\x00\xff\x13\x37")
    assert StreamCheckpoint(state).last_acked() == -1
    # valid JSON but not a dict (legacy/partial writes)
    for payload in ("[1, 2]", '"x"', "3"):
        with open(state, "w") as f:
            f.write(payload)
        assert StreamCheckpoint(state).last_acked() == -1

    # stale .tmp from a crash between write and rename is removed
    import os

    with open(state + ".tmp", "w") as f:
        f.write('{"last_acked": 99}')
    ck2 = StreamCheckpoint(state)
    assert ck2.last_acked() == -1
    assert not os.path.exists(state + ".tmp")

    # and the journal still works after recovery
    ck2.ack(0)
    assert ck2.last_acked() == 0

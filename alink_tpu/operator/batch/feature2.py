"""Feature-engineering breadth: OneHot, PCA, discretizers, binning+WOE,
feature hashing, chi-square selection.

Capability parity with the reference feature package (reference:
core/src/main/java/com/alibaba/alink/operator/batch/feature/
OneHotTrainBatchOp.java:64 + common/feature/OneHotModelMapper.java,
PcaTrainBatchOp.java:53 + common/feature/pca/,
QuantileDiscretizerTrainBatchOp.java, EqualWidthDiscretizerTrainBatchOp.java,
BinningTrainBatchOp.java + common/feature/binning/FeatureBinsCalculator.java
(WOE at common/feature/binning/WoeUtils), FeatureHasherBatchOp.java
(common/feature/FeatureHasherMapper.java), ChiSqSelectorBatchOp.java
(common/feature/ChiSquareSelectorUtil)).

Re-design notes:
- OneHot / StringIndexer token maps are numpy unique passes; serving encodes
  whole blocks at once into one assembled SparseVector per row.
- PCA is an eigendecomposition of the psum-able covariance (MXU matmul Xᵀ X)
  instead of the reference's blocked upload of a packed triangular matrix.
- Binning computes per-bin positive/negative counts with one one-hot matmul
  (same trick as NaiveBayes stats) and derives WOE/IV host-side.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ...common.exceptions import AkIllegalArgumentException
from ...common.linalg import SparseVector, parse_vector
from ...common.model import model_to_table, table_to_model
from ...common.mtable import AlinkTypes, MTable, TableSchema
from ...common.params import InValidator, MinValidator, ParamInfo
from ...mapper import (
    HasOutputCol,
    HasSelectedCol,
    HasReservedCols,
    HasSelectedCols,
    Mapper,
    ModelMapper,
    default_feature_cols,
)
from .base import BatchOperator
from .utils import MapBatchOp, ModelMapBatchOp, ModelTrainOpMixin


# ---------------------------------------------------------------------------
# OneHot
# ---------------------------------------------------------------------------

class OneHotTrainBatchOp(ModelTrainOpMixin, BatchOperator, HasSelectedCols):
    """Distinct-token index per selected column (reference:
    OneHotTrainBatchOp.java:64 — token→index pairs per column)."""

    DROP_LAST = ParamInfo("dropLast", bool, default=True)

    _min_inputs = 1
    _max_inputs = 1

    def _execute_impl(self, t: MTable) -> MTable:
        cols = list(self.get(HasSelectedCols.SELECTED_COLS) or t.names)
        token_maps: Dict[str, List[str]] = {}
        for c in cols:
            vals = np.asarray(t.col(c), dtype=object).astype(str)
            token_maps[c] = sorted(np.unique(vals).tolist())
        meta = {
            "modelName": "OneHotModel",
            "selectedCols": cols,
            "dropLast": self.get(self.DROP_LAST),
            "tokenMaps": token_maps,
        }
        return model_to_table(meta, {})

    def _static_meta_keys(self, in_schema):
        return {"modelName": "OneHotModel",
                "selectedCols": list(self.get(HasSelectedCols.SELECTED_COLS) or
                                     in_schema.names)}


class OneHotModelMapper(ModelMapper, HasOutputCol, HasReservedCols):
    """Encodes the selected columns into ONE assembled sparse vector
    (reference: common/feature/OneHotModelMapper.java, ASSEMBLED_VECTOR
    encode). Unseen tokens map to a per-column "invalid" slot."""

    def load_model(self, model: MTable):
        self.meta, _ = table_to_model(model)
        drop_last = self.meta["dropLast"]
        # Per column with T tokens:
        #   dropLast:  slots = T-1 real (last category → all-zeros) + 1 invalid
        #   else:      slots = T real + 1 invalid
        self.lookups = {}
        self.sizes = []
        for c in self.meta["selectedCols"]:
            tokens = self.meta["tokenMaps"][c]
            T = len(tokens)
            if drop_last:
                lut = {tok: i for i, tok in enumerate(tokens[:-1])}
                size = T  # T-1 real slots + invalid slot at T-1
            else:
                lut = {tok: i for i, tok in enumerate(tokens)}
                size = T + 1  # invalid slot at T
            self.lookups[c] = lut
            self.sizes.append(size)
        self.offsets = np.concatenate([[0], np.cumsum(self.sizes)])
        self.total = int(self.offsets[-1])
        return self

    def output_schema(self, input_schema):
        out = self.get(HasOutputCol.OUTPUT_COL) or "onehot"
        return self._append_result_schema(
            input_schema, [out], [AlinkTypes.SPARSE_VECTOR])

    def map_table(self, t: MTable) -> MTable:
        out = self.get(HasOutputCol.OUTPUT_COL) or "onehot"
        cols = self.meta["selectedCols"]
        drop_last = self.meta["dropLast"]
        n = t.num_rows
        per_col_idx = []
        for j, c in enumerate(cols):
            lut = self.lookups[c]
            tokens = self.meta["tokenMaps"][c]
            invalid_slot = self.sizes[j] - 1
            vals = np.asarray(t.col(c), dtype=object).astype(str)
            idx = np.empty(n, np.int64)
            for i, v in enumerate(vals):
                if v in lut:
                    idx[i] = lut[v] + self.offsets[j]
                elif drop_last and tokens and v == tokens[-1]:
                    idx[i] = -1  # dropped last category → no slot
                else:
                    idx[i] = invalid_slot + self.offsets[j]
            per_col_idx.append(idx)
        stacked = np.stack(per_col_idx, axis=1)  # (n, num_cols)
        vecs = []
        for i in range(n):
            row = stacked[i]
            row = row[row >= 0]
            vecs.append(SparseVector(self.total, row, np.ones(row.size)))
        return self._append_result(
            t, {out: np.asarray(vecs, object)}, {out: AlinkTypes.SPARSE_VECTOR})


class OneHotPredictBatchOp(ModelMapBatchOp, HasOutputCol, HasReservedCols):
    mapper_cls = OneHotModelMapper


# ---------------------------------------------------------------------------
# PCA
# ---------------------------------------------------------------------------

class PcaTrainBatchOp(ModelTrainOpMixin, BatchOperator, HasSelectedCols):
    """(reference: PcaTrainBatchOp.java:53 — covariance/correlation eigen
    decomposition; CALC_TYPE CORR standardizes first)."""

    K = ParamInfo("k", int, optional=False, validator=MinValidator(1))
    CALCULATION_TYPE = ParamInfo(
        "calculationType", str, default="CORR",
        validator=InValidator("CORR", "COV"))
    VECTOR_COL = ParamInfo("vectorCol", str)

    _min_inputs = 1
    _max_inputs = 1

    def _execute_impl(self, t: MTable) -> MTable:
        vec_col = self.get(self.VECTOR_COL)
        if vec_col:
            X = np.stack([parse_vector(v).to_dense().data
                          for v in t.col(vec_col)])
            cols = None
        else:
            cols = list(self.get(HasSelectedCols.SELECTED_COLS) or
                        default_feature_cols(t))
            X = t.to_numeric_block(cols, dtype=np.float64)
        k = int(self.get(self.K))
        mean = X.mean(axis=0)
        std = X.std(axis=0, ddof=0)
        std = np.where(std < 1e-12, 1.0, std)
        if self.get(self.CALCULATION_TYPE) == "CORR":
            Xc = (X - mean) / std
        else:
            Xc = X - mean
            std = np.ones_like(std)
        cov = Xc.T @ Xc / max(X.shape[0] - 1, 1)
        evals, evecs = np.linalg.eigh(cov)
        order = np.argsort(evals)[::-1][:k]
        components = evecs[:, order]          # (d, k)
        variances = np.maximum(evals[order], 0.0)
        meta = {
            "modelName": "PcaModel",
            "selectedCols": cols,
            "vectorCol": vec_col,
            "k": k,
            "calculationType": self.get(self.CALCULATION_TYPE),
            "explainedVarianceRatio":
                [float(v) for v in variances / max(evals.sum(), 1e-300)],
        }
        return model_to_table(meta, {
            "mean": mean, "std": std, "components": components,
            "variances": variances,
        })

    def _static_meta_keys(self, in_schema):
        return {"modelName": "PcaModel", "k": self.get(self.K)}


class PcaModelMapper(ModelMapper, HasOutputCol, HasReservedCols):
    def load_model(self, model: MTable):
        import jax

        self.meta, arrays = table_to_model(model)
        mean, std, W = arrays["mean"], arrays["std"], arrays["components"]
        self._proj = jax.jit(lambda X: ((X - mean) / std) @ W)
        return self

    def output_schema(self, input_schema):
        out = self.get(HasOutputCol.OUTPUT_COL) or "pca"
        return self._append_result_schema(
            input_schema, [out], [AlinkTypes.DENSE_VECTOR])

    def map_table(self, t: MTable) -> MTable:
        import jax

        from ...common.linalg import DenseVector

        out = self.get(HasOutputCol.OUTPUT_COL) or "pca"
        vec_col = self.meta.get("vectorCol")
        if vec_col:
            X = np.stack([parse_vector(v).to_dense().data
                          for v in t.col(vec_col)])
        else:
            X = t.to_numeric_block(self.meta["selectedCols"], dtype=np.float64)
        P = np.asarray(jax.device_get(self._proj(X)))
        vecs = np.asarray([DenseVector(row) for row in P], object)
        return self._append_result(t, {out: vecs},
                                   {out: AlinkTypes.DENSE_VECTOR})


class PcaPredictBatchOp(ModelMapBatchOp, HasOutputCol, HasReservedCols):
    mapper_cls = PcaModelMapper


# ---------------------------------------------------------------------------
# Discretizers
# ---------------------------------------------------------------------------

class _BaseDiscretizerTrainOp(ModelTrainOpMixin, BatchOperator, HasSelectedCols):
    NUM_BUCKETS = ParamInfo("numBuckets", int, default=10,
                            validator=MinValidator(2))

    _min_inputs = 1
    _max_inputs = 1

    model_name: str = None

    def _cuts_for(self, arr: np.ndarray, nb: int) -> List[float]:
        raise NotImplementedError

    def _execute_impl(self, t: MTable) -> MTable:
        cols = list(self.get(HasSelectedCols.SELECTED_COLS) or
                    default_feature_cols(t))
        nb = int(self.get(self.NUM_BUCKETS))
        cutsmap = {}
        for c in cols:
            arr = np.asarray(t.col(c), np.float64)
            cutsmap[c] = [float(v) for v in self._cuts_for(arr[~np.isnan(arr)], nb)]
        meta = {"modelName": self.model_name, "selectedCols": cols,
                "cutsMap": cutsmap}
        return model_to_table(meta, {})

    def _static_meta_keys(self, in_schema):
        cols = list(self.get(HasSelectedCols.SELECTED_COLS) or
                    default_feature_cols(in_schema))
        return {"modelName": self.model_name, "selectedCols": cols}


class QuantileDiscretizerTrainBatchOp(_BaseDiscretizerTrainOp):
    """(reference: QuantileDiscretizerTrainBatchOp.java — distributed quantile
    sketch collapses to one sort per column)."""

    model_name = "QuantileDiscretizerModel"

    def _cuts_for(self, arr, nb):
        qs = np.quantile(arr, np.linspace(0, 1, nb + 1)[1:-1]) if arr.size else []
        return sorted(set(float(q) for q in qs))


class EqualWidthDiscretizerTrainBatchOp(_BaseDiscretizerTrainOp):
    """(reference: EqualWidthDiscretizerTrainBatchOp.java)."""

    model_name = "EqualWidthDiscretizerModel"

    def _cuts_for(self, arr, nb):
        if not arr.size:
            return []
        lo, hi = float(arr.min()), float(arr.max())
        if hi <= lo:
            return []
        return list(np.linspace(lo, hi, nb + 1)[1:-1])


class DiscretizerModelMapper(ModelMapper, HasReservedCols):
    """Replaces each selected column by its LONG bucket index (reference:
    common/feature/QuantileDiscretizerModelMapper.java)."""

    def load_model(self, model: MTable):
        self.meta, _ = table_to_model(model)
        self.cuts = {c: np.asarray(v, np.float64)
                     for c, v in self.meta["cutsMap"].items()}
        return self

    def output_schema(self, input_schema):
        cols = set(self.meta["selectedCols"])
        types = [AlinkTypes.LONG if n in cols else t
                 for n, t in zip(input_schema.names, input_schema.types)]
        return TableSchema(list(input_schema.names), types)

    def map_table(self, t: MTable) -> MTable:
        out = t
        for c in self.meta["selectedCols"]:
            arr = np.asarray(t.col(c), np.float64)
            idx = np.searchsorted(self.cuts[c], arr, side="right")
            out = out.with_column(c, idx.astype(np.int64), AlinkTypes.LONG)
        return out


class QuantileDiscretizerPredictBatchOp(ModelMapBatchOp, HasReservedCols):
    mapper_cls = DiscretizerModelMapper


class EqualWidthDiscretizerPredictBatchOp(ModelMapBatchOp, HasReservedCols):
    mapper_cls = DiscretizerModelMapper


# ---------------------------------------------------------------------------
# Binning + WOE
# ---------------------------------------------------------------------------

class BinningTrainBatchOp(ModelTrainOpMixin, BatchOperator, HasSelectedCols):
    """Numeric binning with per-bin WOE/IV against a binary label
    (reference: BinningTrainBatchOp.java + common/feature/binning/
    FeatureBinsCalculator.java; WOE = ln(posRate/negRate) per bin)."""

    LABEL_COL = ParamInfo("labelCol", str, optional=False)
    NUM_BUCKETS = ParamInfo("numBuckets", int, default=10,
                            validator=MinValidator(2))
    POSITIVE_LABEL = ParamInfo("positiveLabelValueString", str,
                               aliases=("positiveValue",))
    BINNING_METHOD = ParamInfo(
        "binningMethod", str, default="QUANTILE",
        validator=InValidator("QUANTILE", "BUCKET"))

    _min_inputs = 1
    _max_inputs = 1

    def _execute_impl(self, t: MTable) -> MTable:
        label_col = self.get(self.LABEL_COL)
        cols = list(self.get(HasSelectedCols.SELECTED_COLS) or
                    default_feature_cols(t, exclude=[label_col]))
        nb = int(self.get(self.NUM_BUCKETS))
        y_raw = np.asarray(t.col(label_col), dtype=object).astype(str)
        pos_label = self.get(self.POSITIVE_LABEL)
        if pos_label is None:
            pos_label = sorted(np.unique(y_raw).tolist())[0]
        y = (y_raw == str(pos_label)).astype(np.float64)
        total_pos = max(y.sum(), 0.5)
        total_neg = max((1 - y).sum(), 0.5)

        cutsmap, woemap, ivmap, statsmap = {}, {}, {}, {}
        for c in cols:
            arr = np.asarray(t.col(c), np.float64)
            ok = ~np.isnan(arr)
            if self.get(self.BINNING_METHOD) == "QUANTILE":
                qs = np.quantile(arr[ok], np.linspace(0, 1, nb + 1)[1:-1])
                cuts = sorted(set(float(q) for q in qs))
            else:
                lo, hi = float(arr[ok].min()), float(arr[ok].max())
                cuts = list(np.linspace(lo, hi, nb + 1)[1:-1]) if hi > lo else []
            idx = np.searchsorted(np.asarray(cuts), arr, side="right")
            k = len(cuts) + 1
            pos = np.zeros(k)
            neg = np.zeros(k)
            np.add.at(pos, idx[ok], y[ok])
            np.add.at(neg, idx[ok], 1 - y[ok])
            # smoothed WOE: ln((pos_i/total_pos)/(neg_i/total_neg))
            pr = np.maximum(pos, 0.5) / total_pos
            nr = np.maximum(neg, 0.5) / total_neg
            woe = np.log(pr / nr)
            iv = float(((pr - nr) * woe).sum())
            cutsmap[c] = cuts
            woemap[c] = [float(v) for v in woe]
            ivmap[c] = iv
            statsmap[c] = {"positive": [float(v) for v in pos],
                           "negative": [float(v) for v in neg]}
        meta = {
            "modelName": "BinningModel",
            "selectedCols": cols,
            "labelCol": label_col,
            "positiveLabel": str(pos_label),
            "cutsMap": cutsmap,
            "woeMap": woemap,
            "ivMap": ivmap,
            "binStats": statsmap,
        }
        return model_to_table(meta, {})

    def _static_meta_keys(self, in_schema):
        label_col = self.get(self.LABEL_COL)
        cols = list(self.get(HasSelectedCols.SELECTED_COLS) or
                    default_feature_cols(in_schema, exclude=[label_col]))
        return {"modelName": "BinningModel", "selectedCols": cols}


class BinningModelMapper(ModelMapper, HasReservedCols):
    """encode=WOE replaces values by bin WOE (DOUBLE); encode=INDEX by the
    LONG bin id (reference: common/feature/binning/BinningModelMapper.java)."""

    ENCODE = ParamInfo("encode", str, default="WOE",
                       validator=InValidator("WOE", "INDEX"))

    def load_model(self, model: MTable):
        self.meta, _ = table_to_model(model)
        self.cuts = {c: np.asarray(v, np.float64)
                     for c, v in self.meta["cutsMap"].items()}
        self.woe = {c: np.asarray(v, np.float64)
                    for c, v in self.meta["woeMap"].items()}
        return self

    def output_schema(self, input_schema):
        cols = set(self.meta["selectedCols"])
        enc = self.get(self.ENCODE)
        tag = AlinkTypes.DOUBLE if enc == "WOE" else AlinkTypes.LONG
        types = [tag if n in cols else t
                 for n, t in zip(input_schema.names, input_schema.types)]
        return TableSchema(list(input_schema.names), types)

    def map_table(self, t: MTable) -> MTable:
        enc = self.get(self.ENCODE)
        out = t
        for c in self.meta["selectedCols"]:
            arr = np.asarray(t.col(c), np.float64)
            idx = np.searchsorted(self.cuts[c], arr, side="right")
            if enc == "WOE":
                out = out.with_column(c, self.woe[c][idx], AlinkTypes.DOUBLE)
            else:
                out = out.with_column(c, idx.astype(np.int64), AlinkTypes.LONG)
        return out


class BinningPredictBatchOp(ModelMapBatchOp, HasReservedCols):
    mapper_cls = BinningModelMapper
    ENCODE = BinningModelMapper.ENCODE


# ---------------------------------------------------------------------------
# Feature hashing (stateless)
# ---------------------------------------------------------------------------

def _hash32(s: str) -> int:
    """Deterministic FNV-1a 32-bit (stable across processes, unlike hash())."""
    h = 0x811C9DC5
    for b in s.encode("utf-8"):
        h = ((h ^ b) * 0x01000193) & 0xFFFFFFFF
    return h


class FeatureHasherMapper(Mapper, HasSelectedCols, HasOutputCol, HasReservedCols):
    """Hashing-trick sparse encoding of mixed categorical/numeric columns
    (reference: common/feature/FeatureHasherMapper.java)."""

    NUM_FEATURES = ParamInfo("numFeatures", int, default=262144,
                             validator=MinValidator(2))
    CATEGORICAL_COLS = ParamInfo("categoricalCols", list)

    def output_schema(self, input_schema):
        out = self.get(HasOutputCol.OUTPUT_COL) or "hashed"
        return self._append_result_schema(
            input_schema, [out], [AlinkTypes.SPARSE_VECTOR])

    def map_table(self, t: MTable) -> MTable:
        out = self.get(HasOutputCol.OUTPUT_COL) or "hashed"
        cols = list(self.get(HasSelectedCols.SELECTED_COLS) or t.names)
        cat_cols = set(self.get(self.CATEGORICAL_COLS) or
                       [c for c in cols
                        if not AlinkTypes.is_numeric(t.schema.type_of(c))])
        m = int(self.get(self.NUM_FEATURES))
        n = t.num_rows
        acc: List[Dict[int, float]] = [dict() for _ in range(n)]
        for c in cols:
            vals = t.col(c)
            if c in cat_cols:
                for i, v in enumerate(vals):
                    slot = _hash32(f"{c}={v}") % m
                    acc[i][slot] = acc[i].get(slot, 0.0) + 1.0
            else:
                slot = _hash32(c) % m
                arr = np.asarray(vals, np.float64)
                for i in range(n):
                    acc[i][slot] = acc[i].get(slot, 0.0) + float(arr[i])
        vecs = np.asarray(
            [SparseVector(m, list(d.keys()), list(d.values())) for d in acc],
            object)
        return self._append_result(t, {out: vecs},
                                   {out: AlinkTypes.SPARSE_VECTOR})


class FeatureHasherBatchOp(MapBatchOp, HasSelectedCols, HasOutputCol,
                           HasReservedCols):
    mapper_cls = FeatureHasherMapper
    NUM_FEATURES = FeatureHasherMapper.NUM_FEATURES
    CATEGORICAL_COLS = FeatureHasherMapper.CATEGORICAL_COLS


# ---------------------------------------------------------------------------
# Chi-square feature selection
# ---------------------------------------------------------------------------

class ChiSqSelectorBatchOp(ModelTrainOpMixin, BatchOperator, HasSelectedCols):
    """Select top-k features by chi-square score against the label
    (reference: ChiSqSelectorBatchOp.java)."""

    LABEL_COL = ParamInfo("labelCol", str, optional=False)
    NUM_TOP_FEATURES = ParamInfo("numTopFeatures", int, default=50,
                                 validator=MinValidator(1))

    _min_inputs = 1
    _max_inputs = 1

    def _execute_impl(self, t: MTable) -> MTable:
        from .statistics import _contingency, chi_square_test

        label_col = self.get(self.LABEL_COL)
        cols = list(self.get(HasSelectedCols.SELECTED_COLS) or
                    default_feature_cols(t, exclude=[label_col]))
        y = t.col(label_col)
        scores = []
        for c in cols:
            stat, p, _ = chi_square_test(_contingency(t.col(c), y))
            scores.append((c, stat, p))
        k = min(int(self.get(self.NUM_TOP_FEATURES)), len(cols))
        top = sorted(scores, key=lambda s: -s[1])[:k]
        meta = {
            "modelName": "ChiSqSelectorModel",
            "selectedCols": cols,
            "siftOutCols": [c for c, _, _ in top],
            "chi2": {c: float(s) for c, s, _ in scores},
            "pValues": {c: float(p) for c, _, p in scores},
        }
        return model_to_table(meta, {})

    def _static_meta_keys(self, in_schema):
        return {"modelName": "ChiSqSelectorModel"}


class ChiSqSelectorModelMapper(ModelMapper, HasReservedCols):
    """Projects the table onto the selected feature columns."""

    def load_model(self, model: MTable):
        self.meta, _ = table_to_model(model)
        return self

    def output_schema(self, input_schema):
        keep = [n for n in input_schema.names
                if n in self.meta["siftOutCols"] or
                n not in self.meta["selectedCols"]]
        return TableSchema(keep, [input_schema.type_of(n) for n in keep])

    def map_table(self, t: MTable) -> MTable:
        schema = self.output_schema(t.schema)
        return MTable({n: t.col(n) for n in schema.names}, schema)


class ChiSqSelectorPredictBatchOp(ModelMapBatchOp, HasReservedCols):
    mapper_cls = ChiSqSelectorModelMapper


# ---------------------------------------------------------------------------
# MaxAbsScaler
# ---------------------------------------------------------------------------

class MaxAbsScalerTrainBatchOp(ModelTrainOpMixin, BatchOperator, HasSelectedCols):
    """(reference: MaxAbsScalerTrainBatchOp.java)"""

    _min_inputs = 1
    _max_inputs = 1

    def _execute_impl(self, t: MTable) -> MTable:
        cols = list(self.get(HasSelectedCols.SELECTED_COLS) or
                    default_feature_cols(t))
        X = t.to_numeric_block(cols, dtype=np.float64)
        meta = {"modelName": "MaxAbsScalerModel", "selectedCols": cols}
        return model_to_table(meta, {"maxAbs": np.abs(X).max(axis=0)})

    def _static_meta_keys(self, in_schema):
        cols = list(self.get(HasSelectedCols.SELECTED_COLS) or
                    default_feature_cols(in_schema))
        return {"modelName": "MaxAbsScalerModel", "selectedCols": cols}


class MaxAbsScalerModelMapper(ModelMapper, HasReservedCols):
    def load_model(self, model: MTable):
        self.meta, arrays = table_to_model(model)
        self.scale = np.where(arrays["maxAbs"] < 1e-12, 1.0, arrays["maxAbs"])
        return self

    def output_schema(self, input_schema):
        cols = set(self.meta["selectedCols"])
        types = [AlinkTypes.DOUBLE if n in cols else t
                 for n, t in zip(input_schema.names, input_schema.types)]
        return TableSchema(list(input_schema.names), types)

    def map_table(self, t: MTable) -> MTable:
        out = t
        for i, c in enumerate(self.meta["selectedCols"]):
            v = np.asarray(t.col(c), np.float64) / self.scale[i]
            out = out.with_column(c, v, AlinkTypes.DOUBLE)
        return out


class MaxAbsScalerPredictBatchOp(ModelMapBatchOp, HasReservedCols):
    mapper_cls = MaxAbsScalerModelMapper


class DCTMapper(Mapper, HasSelectedCol, HasOutputCol, HasReservedCols):
    """Orthonormal DCT-II of a vector column (reference:
    operator/batch/feature/DCTBatchOp.java + common/feature/DCTMapper)."""

    INVERSE = ParamInfo("inverse", bool, default=False)

    def output_schema(self, input_schema):
        out = (self.get(HasOutputCol.OUTPUT_COL) or
               self.get(HasSelectedCol.SELECTED_COL))
        return self._append_result_schema(
            input_schema, [out], [AlinkTypes.DENSE_VECTOR])

    def map_table(self, t: MTable) -> MTable:
        from ...common.linalg import DenseVector

        col = self.get(HasSelectedCol.SELECTED_COL)
        out = self.get(HasOutputCol.OUTPUT_COL) or col
        X = np.stack([parse_vector(v).to_dense().data for v in t.col(col)])
        n = X.shape[1]
        k = np.arange(n)
        basis = np.cos(np.pi / n * (k[:, None] + 0.5) * k[None, :])
        basis *= np.sqrt(2.0 / n)
        basis[:, 0] = np.sqrt(1.0 / n)
        Y = X @ basis.T if self.get(self.INVERSE) else X @ basis
        vecs = np.asarray([DenseVector(row) for row in Y], object)
        return self._append_result(t, {out: vecs},
                                   {out: AlinkTypes.DENSE_VECTOR})


class DCTBatchOp(MapBatchOp, HasSelectedCol, HasOutputCol, HasReservedCols):
    mapper_cls = DCTMapper
    INVERSE = DCTMapper.INVERSE


class AutoCrossBatchOp(ModelTrainOpMixin, BatchOperator):
    """Greedy categorical feature-cross search (reference:
    operator/batch/feature/AutoCrossTrainBatchOp.java + common/fe AutoCross —
    beam search over crosses scored by downstream LR gain).

    Re-design (compact): candidate pairwise crosses of the categorical
    columns are scored by the holdout AUC gain of a logistic regression on
    (base one-hot + cross one-hot); the top ``numCross`` winners persist in
    the model, and serving appends each cross as a combined categorical
    column crossed_a_b = "a=..#b=..". Chain OneHot afterwards for vectors."""

    CATEGORICAL_COLS = ParamInfo("categoricalCols", list, optional=False)
    LABEL_COL = ParamInfo("labelCol", str, optional=False)
    NUM_CROSS = ParamInfo("numCross", int, default=2,
                          validator=MinValidator(1))
    POSITIVE_LABEL = ParamInfo("positiveLabelValueString", str)
    RANDOM_SEED = ParamInfo("randomSeed", int, default=0, aliases=("seed",))

    _min_inputs = 1
    _max_inputs = 1

    def _encode(self, cols_vals):
        """one-hot index encode a list of string columns -> CSR-ish dense."""
        mats = []
        for vals in cols_vals:
            uniq, inv = np.unique(vals, return_inverse=True)
            m = np.zeros((len(vals), len(uniq)), np.float32)
            m[np.arange(len(vals)), inv] = 1.0
            mats.append(m)
        return np.concatenate(mats, axis=1) if mats else \
            np.zeros((0, 0), np.float32)

    def _auc(self, X, y, seed):
        from ...optim import logistic_obj, optimize
        from .evaluation import rank_auc

        rng = np.random.default_rng(seed)
        n = len(y)
        perm = rng.permutation(n)
        cut = int(n * 0.7)
        tr, te = perm[:cut], perm[cut:]
        Xb = np.concatenate([X, np.ones((n, 1), np.float32)], axis=1)
        res = optimize(logistic_obj(Xb.shape[1]), Xb[tr], y[tr],
                       max_iter=40, l2=1e-3)
        scores = Xb[te] @ res.weights
        return rank_auc(scores, y[te] > 0)

    def _execute_impl(self, t: MTable) -> MTable:
        from itertools import combinations

        cols = list(self.get(self.CATEGORICAL_COLS))
        label_col = self.get(self.LABEL_COL)
        y_raw = np.asarray(t.col(label_col), object).astype(str)
        pos = self.get(self.POSITIVE_LABEL) or sorted(set(y_raw))[0]
        y = np.where(y_raw == str(pos), 1.0, -1.0).astype(np.float32)
        seed = self.get(self.RANDOM_SEED)

        base_vals = {c: np.asarray(t.col(c), object).astype(str)
                     for c in cols}
        base_X = self._encode([base_vals[c] for c in cols])
        base_auc = self._auc(base_X, y, seed)

        scored = []
        for a, b in combinations(cols, 2):
            crossed = np.asarray(
                [f"{x}#{z}" for x, z in zip(base_vals[a], base_vals[b])],
                object)
            X = np.concatenate(
                [base_X, self._encode([crossed])], axis=1)
            gain = self._auc(X, y, seed) - base_auc
            scored.append(((a, b), float(gain)))
        scored.sort(key=lambda s: -s[1])
        chosen = [list(pair) for pair, gain in
                  scored[:self.get(self.NUM_CROSS)] if gain > 0]
        meta = {
            "modelName": "AutoCrossModel",
            "categoricalCols": cols,
            "crosses": chosen,
            "baseAuc": float(base_auc),
            "gains": {f"{a}#{b}": g for (a, b), g in scored},
        }
        return model_to_table(meta, {})

    def _static_meta_keys(self, in_schema):
        return {"modelName": "AutoCrossModel"}


class AutoCrossModelMapper(ModelMapper, HasReservedCols):
    """Appends one combined categorical column per learned cross."""

    def load_model(self, model: MTable):
        self.meta, _ = table_to_model(model)
        return self

    def output_schema(self, input_schema):
        names = list(input_schema.names)
        types = list(input_schema.types)
        for a, b in self.meta["crosses"]:
            names.append(f"cross_{a}_{b}")
            types.append(AlinkTypes.STRING)
        return TableSchema(names, types)

    def map_table(self, t: MTable) -> MTable:
        out = t
        for a, b in self.meta["crosses"]:
            va = np.asarray(t.col(a), object).astype(str)
            vb = np.asarray(t.col(b), object).astype(str)
            crossed = np.asarray([f"{x}#{z}" for x, z in zip(va, vb)], object)
            out = out.with_column(f"cross_{a}_{b}", crossed, AlinkTypes.STRING)
        return out


class AutoCrossPredictBatchOp(ModelMapBatchOp, HasReservedCols):
    mapper_cls = AutoCrossModelMapper

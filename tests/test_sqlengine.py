"""SQL-string engine + JDBC + catalog tests (reference:
operator/common/sql/MTableCalciteSqlExecutor.java, common/io/catalog/)."""

import numpy as np
import pytest

from alink_tpu.common.mtable import MTable
from alink_tpu.operator.batch import (
    JdbcSinkBatchOp,
    JdbcSourceBatchOp,
    MemSourceBatchOp,
    SqliteCatalog,
    SqlQueryBatchOp,
    sql_query,
)


def test_sql_query_function():
    t = MTable({"a": np.asarray([1, 2, 3], np.int64),
                "b": np.asarray(["x", "y", "x"], object)})
    out = sql_query(
        "SELECT b, SUM(a) AS total FROM t GROUP BY b ORDER BY b",
        {"t": t})
    assert list(out.col("b")) == ["x", "y"]
    assert list(out.col("total")) == [4, 2]


def test_sql_query_op_joins_two_inputs():
    left = MemSourceBatchOp([(1, "ann"), (2, "bob")], "id bigint, name string")
    right = MemSourceBatchOp([(1, 95.5), (2, 88.0), (1, 70.0)],
                             "id bigint, score double")
    out = SqlQueryBatchOp(
        query="SELECT t0.name, AVG(t1.score) AS avg_score "
              "FROM t0 JOIN t1 ON t0.id = t1.id "
              "GROUP BY t0.name ORDER BY t0.name").link_from(left, right) \
        .collect()
    assert list(out.col("name")) == ["ann", "bob"]
    assert out.col("avg_score")[0] == pytest.approx(82.75)


def test_sql_window_function():
    src = MemSourceBatchOp([(1, 10.0), (1, 20.0), (2, 5.0)],
                           "g bigint, v double")
    out = SqlQueryBatchOp(
        query="SELECT g, v, RANK() OVER (PARTITION BY g ORDER BY v DESC) r "
              "FROM t ORDER BY g, r").link_from(src).collect()
    assert list(out.col("r")) == [1, 2, 1]


def test_jdbc_roundtrip_and_catalog(tmp_path):
    db = str(tmp_path / "warehouse.db")
    src = MemSourceBatchOp([(1, 2.5, "a"), (2, float("nan"), "b")],
                           "id bigint, v double, s string")
    JdbcSinkBatchOp(dbPath=db, tableName="stuff").link_from(src).collect()

    cat = SqliteCatalog(db)
    assert cat.list_tables() == ["stuff"]
    schema = cat.get_table_schema("stuff")
    assert schema.type_of("id") == "LONG"
    assert schema.type_of("v") == "DOUBLE"

    out = JdbcSourceBatchOp(dbPath=db, tableName="stuff").link_from() \
        .collect()
    assert list(out.col("id")) == [1, 2]
    assert np.isnan(out.col("v")[1])     # NaN -> NULL -> NaN roundtrip
    out2 = JdbcSourceBatchOp(
        dbPath=db, query="SELECT s FROM stuff WHERE id = 2").link_from() \
        .collect()
    assert list(out2.col("s")) == ["b"]
    cat.drop_table("stuff")
    assert cat.list_tables() == []


def test_sql_query_static_schema_typed():
    src = MemSourceBatchOp([(1, 2.5)], "a bigint, v double")
    op = SqlQueryBatchOp(query="SELECT a + 1 AS b, v * 2 AS w FROM t") \
        .link_from(src)
    # static schema (no execution of the real data) carries real types
    assert op.schema.type_of("b") == "LONG"
    assert op.schema.type_of("w") == "DOUBLE"

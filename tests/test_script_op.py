"""JaxScriptBatchOp / JaxScriptStreamOp — the user-script execution ops.

(reference: operator/batch/tensorflow/TensorFlow2BatchOp.java,
operator/stream/tensorflow/TensorFlow2StreamOp.java)
"""

import numpy as np
import pytest

from alink_tpu.common.exceptions import AkIllegalArgumentException
from alink_tpu.common.mtable import MTable
from alink_tpu.operator.batch import JaxScriptBatchOp, TensorFlow2BatchOp
from alink_tpu.operator.batch.base import TableSourceBatchOp
from alink_tpu.operator.stream import JaxScriptStreamOp, TensorFlowStreamOp
from alink_tpu.operator.stream.base import TableSourceStreamOp

USER_SCRIPT = '''
"""User training script: fits a tiny flax regressor on the op's dataset
iterator, mesh in hand, and outputs predictions — what the reference's
TensorFlow2BatchOp user scripts do on a TF cluster."""
import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn


class Net(nn.Module):
    @nn.compact
    def __call__(self, x):
        x = nn.relu(nn.Dense(16)(x))
        return nn.Dense(1)(x)[:, 0]


def main(ctx):
    assert ctx.mesh is not None  # the session mesh is handed in
    lr = float(ctx.user_params.get("lr", 1e-2))
    model = Net()
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 2), jnp.float32))
    tx = optax.adam(lr)
    opt = tx.init(params)

    @jax.jit
    def step(params, opt, x, y):
        def loss(p):
            return jnp.mean((model.apply(p, x) - y) ** 2)
        g = jax.grad(loss)(params)
        up, opt = tx.update(g, opt)
        return optax.apply_updates(params, up), opt

    for batch in ctx.dataset(batch_size=32, epochs=40):
        x = jnp.stack([batch["a"], batch["b"]], axis=1).astype(jnp.float32)
        y = jnp.asarray(batch["y"], jnp.float32)
        params, opt = step(params, opt, x, y)

    t = ctx.table(0)
    xs = np.stack([np.asarray(t.col("a")), np.asarray(t.col("b"))], axis=1)
    pred = np.asarray(model.apply(params, jnp.asarray(xs, jnp.float32)))
    ctx.output({"a": np.asarray(t.col("a")), "pred": pred})
'''


def _data(n=256, seed=0):
    rng = np.random.default_rng(seed)
    a = rng.normal(size=n)
    b = rng.normal(size=n)
    y = 2.0 * a - 3.0 * b + 0.5
    return MTable({"a": a, "b": b, "y": y})


def test_script_file_trains_flax_model(tmp_path):
    path = tmp_path / "user_train.py"
    path.write_text(USER_SCRIPT)
    t = _data()
    out = JaxScriptBatchOp(
        mainScriptFile=str(path), userParams='{"lr": 0.02}',
    ).link_from(TableSourceBatchOp(t)).collect()
    assert out.names == ["a", "pred"]
    truth = 2.0 * np.asarray(t.col("a")) - 3.0 * np.asarray(t.col("b")) + 0.5
    mse = float(np.mean((np.asarray(out.col("pred")) - truth) ** 2))
    assert mse < 0.1, mse  # the script really learned the function


def test_user_fn_and_output_schema():
    def main(ctx):
        t = ctx.table(0)
        return {"s": np.asarray(t.col("a")) + np.asarray(t.col("b"))}

    t = _data(32)
    out = JaxScriptBatchOp(
        userFn=main, outputSchemaStr="s double",
    ).link_from(TableSourceBatchOp(t)).collect()
    np.testing.assert_allclose(
        np.asarray(out.col("s")),
        np.asarray(t.col("a")) + np.asarray(t.col("b")))

    with pytest.raises(AkIllegalArgumentException, match="declares"):
        JaxScriptBatchOp(
            userFn=main, outputSchemaStr="wrong double",
        ).link_from(TableSourceBatchOp(t)).collect()


def test_legacy_func_shim_still_works():
    t = _data(16)
    out = TensorFlow2BatchOp(
        func=lambda df: df.assign(z=df.a * 2),
    ).link_from(TableSourceBatchOp(t)).collect()
    np.testing.assert_allclose(np.asarray(out.col("z")),
                               2 * np.asarray(t.col("a")))


def test_stream_script_chunks_and_emit():
    def main(ctx):
        assert ctx.mesh is not None
        total = 0.0
        for chunk in ctx.chunks():
            total += float(np.sum(np.asarray(chunk.col("a"))))
            ctx.emit({"running_sum": np.asarray([total])})

    t = _data(64)
    out = JaxScriptStreamOp(userFn=main).link_from(
        TableSourceStreamOp(t, chunkSize=16)).collect()
    sums = np.asarray(out.col("running_sum"))
    assert len(sums) == 4
    np.testing.assert_allclose(sums[-1], np.sum(np.asarray(t.col("a"))),
                               rtol=1e-6)


def test_stream_legacy_func_per_chunk():
    t = _data(48)
    out = TensorFlowStreamOp(
        func=lambda df: df.assign(n=df.a + 1),
    ).link_from(TableSourceStreamOp(t, chunkSize=16)).collect()
    assert out.num_rows == 48
    np.testing.assert_allclose(np.asarray(out.col("n")),
                               np.asarray(t.col("a")) + 1)

"""Recommendation core: ALS factorization + neighborhood CF + Swing.

(reference: core/.../operator/common/recommendation/ — HugeMfAlsImpl,
ItemCf/UserCf kernels, Swing, and the RecommKernel serving layer.)
"""

from .als import AlsModelData, train_als
from .cf import interaction_similarity, swing_similarity

__all__ = [
    "AlsModelData", "train_als",
    "interaction_similarity", "swing_similarity",
]

"""Pretrained BERT checkpoint ingest — the BertResources analog.

The reference ships pretrained BERT vocab + checkpoints through its
resource-plugin system and fine-tunes from them (reference:
core/src/main/java/com/alibaba/alink/common/dl/BertResources.java:28,76-85;
consumed by common/dl/BaseEasyTransferTrainBatchOp.java). This build runs
zero-egress, so resources are resolved from the local plugin directory
(``MLEnvironment.get_plugin_dir()``), same contract as the reference's
pre-downloaded plugin layout — the user drops a checkpoint directory there
(or passes an explicit path) and the BERT ops fine-tune from it.

Supported on-disk formats (auto-detected):
- HuggingFace layout: ``config.json`` + ``model.safetensors`` /
  ``pytorch_model.bin`` / ``flax_model.msgpack`` + ``vocab.txt``
- google-research TF v1 checkpoint: ``bert_config.json`` +
  ``bert_model.ckpt.{index,data-*}`` + ``vocab.txt`` (the exact artifact the
  reference's CKPT resources unpack, e.g. uncased_L-12_H-768_A-12.zip)

Weights map into :class:`alink_tpu.dl.modules.TransformerEncoder`'s tree
(qkv fused, ``pool="cls"`` for pretrained fidelity); the classifier head is
freshly initialised, which is what fine-tuning means.
"""

from __future__ import annotations

import json
import os
import struct
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ..common.exceptions import (AkIllegalArgumentException,
                                 AkPluginNotExistException)

# normalized model names accepted by ``bertModelName`` (reference enum
# BertResources.ModelName) -> plugin subdirectory
MODEL_NAME_DIRS = {
    "base-uncased": "bert-base-uncased",
    "base-cased": "bert-base-cased",
    "base-chinese": "bert-base-chinese",
    "base-multilingual-cased": "bert-base-multilingual-cased",
}


def _normalize_model_name(name: str) -> str:
    n = name.strip().lower().replace("_", "-")
    if n.startswith("bert-"):
        n = n[len("bert-"):]
    return n


def resolve_bert_resource(model_name: str) -> str:
    """Resolve ``bertModelName`` to a local checkpoint directory under the
    plugin dir, or raise naming exactly what to place where (the zero-egress
    stand-in for the reference's resource downloader)."""
    from ..common.env import AlinkGlobalConfiguration

    n = _normalize_model_name(model_name)
    sub = MODEL_NAME_DIRS.get(n, f"bert-{n}")
    root = AlinkGlobalConfiguration.get_plugin_dir()
    cand = os.path.join(root, "bert", sub)
    if os.path.isdir(cand) and _detect_format(cand) is not None:
        return cand
    raise AkPluginNotExistException(
        f"pretrained BERT resource {model_name!r} not found: place a "
        f"checkpoint directory at {cand} (HuggingFace layout with "
        f"config.json + model.safetensors + vocab.txt, or a google-research "
        f"TF checkpoint with bert_config.json + bert_model.ckpt.* + "
        f"vocab.txt). The reference downloads these through its resource "
        f"plugin (BertResources.java); this build is zero-egress, so the "
        f"files must be staged locally."
    )


def _detect_format(path: str) -> Optional[str]:
    if os.path.isfile(os.path.join(path, "model.safetensors")):
        return "safetensors"
    if os.path.isfile(os.path.join(path, "pytorch_model.bin")):
        return "torch"
    if os.path.isfile(os.path.join(path, "flax_model.msgpack")):
        return "flax"
    for f in os.listdir(path) if os.path.isdir(path) else []:
        if f.endswith(".ckpt.index") or f.endswith(".ckpt.meta"):
            return "tf_ckpt"
    return None


# ---------------------------------------------------------------------------
# raw tensor readers -> flat {hf_style_name: np.ndarray}
# ---------------------------------------------------------------------------


def _read_safetensors(path: str) -> Dict[str, np.ndarray]:
    """Minimal standalone safetensors reader (header is JSON; tensors are
    raw little-endian buffers). Avoids framework tensor detours."""
    _DT = {
        "F64": np.float64, "F32": np.float32, "F16": np.float16,
        "BF16": None, "I64": np.int64, "I32": np.int32, "I16": np.int16,
        "I8": np.int8, "U8": np.uint8, "BOOL": np.bool_,
    }
    out: Dict[str, np.ndarray] = {}
    with open(path, "rb") as f:
        (hlen,) = struct.unpack("<Q", f.read(8))
        header = json.loads(f.read(hlen))
        blob = f.read()
    for name, info in header.items():
        if name == "__metadata__":
            continue
        a, b = info["data_offsets"]
        raw = blob[a:b]
        if info["dtype"] == "BF16":
            u16 = np.frombuffer(raw, np.uint16).astype(np.uint32) << 16
            arr = u16.view(np.float32)
        else:
            arr = np.frombuffer(raw, _DT[info["dtype"]])
        out[name] = arr.reshape(info["shape"]).copy()
    return out


def _read_torch_bin(path: str) -> Dict[str, np.ndarray]:
    import torch

    state = torch.load(path, map_location="cpu", weights_only=True)
    return {k: v.float().numpy() for k, v in state.items()}


def _read_flax_msgpack(path: str) -> Dict[str, np.ndarray]:
    from flax import serialization, traverse_util

    with open(path, "rb") as f:
        tree = serialization.msgpack_restore(f.read())
    flat = traverse_util.flatten_dict(tree, sep=".")
    # HF flax names: embeddings.word_embeddings.embedding etc. Convert to the
    # torch-style names the mapper below understands. Renames are anchored to
    # the last path segment ("...embeddings" must not become "...weights").
    out = {}
    for k, v in flat.items():
        if k.endswith(".embedding"):
            k = k[: -len(".embedding")] + ".weight"
        elif k.endswith(".kernel"):  # flax kernels are already (in, out)
            k = k[: -len(".kernel")] + ".weight_t"
        elif k.endswith(".scale"):
            k = k[: -len(".scale")] + ".weight"
        out[k] = np.asarray(v)
    return out


def _read_tf_ckpt(path: str) -> Dict[str, np.ndarray]:
    """google-research BERT v1 checkpoint -> HF-style names.

    TF variable names (bert/encoder/layer_0/attention/self/query/kernel, ...)
    are renamed; TF kernels are already (in, out) so they're tagged
    ``weight_t`` to skip the torch transpose."""
    import tensorflow as tf

    reader = tf.train.load_checkpoint(path)
    shapes = reader.get_variable_to_shape_map()
    out: Dict[str, np.ndarray] = {}
    for var in shapes:
        if not var.startswith("bert/") or "adam" in var.lower():
            continue
        name = var[len("bert/"):]
        name = (name.replace("/", ".")
                    .replace("encoder.layer_", "encoder.layer.")
                    .replace("LayerNorm.gamma", "LayerNorm.weight")
                    .replace("LayerNorm.beta", "LayerNorm.bias")
                    .replace(".kernel", ".weight_t"))
        if name.startswith("embeddings.") and name.endswith("_embeddings"):
            name += ".weight"
        out[name] = np.asarray(reader.get_tensor(var))
    return out


def _infer_do_lower_case(path: str, hf_cfg: Dict[str, Any]) -> bool:
    """HF keeps the casing flag in tokenizer_config.json, not config.json;
    google bert_config.json has neither. Fall back to the directory name
    ('-cased' checkpoints must not be lowercased/accent-stripped)."""
    tc = os.path.join(path, "tokenizer_config.json")
    if os.path.isfile(tc):
        with open(tc) as f:
            v = json.load(f).get("do_lower_case")
        if v is not None:
            return bool(v)
    if "do_lower_case" in hf_cfg:
        return bool(hf_cfg["do_lower_case"])
    base = os.path.basename(os.path.normpath(path)).lower()
    if "uncased" in base:
        return True
    if "cased" in base or "chinese" in base or "multilingual" in base:
        return False
    return True


def _load_config(path: str) -> Dict[str, Any]:
    for fname in ("config.json", "bert_config.json"):
        p = os.path.join(path, fname)
        if os.path.isfile(p):
            with open(p) as f:
                return json.load(f)
    raise AkIllegalArgumentException(
        f"no config.json / bert_config.json under {path}")


def load_vocab_file(path: str) -> "list[str]":
    p = os.path.join(path, "vocab.txt") if os.path.isdir(path) else path
    if not os.path.isfile(p):
        raise AkPluginNotExistException(
            f"vocab.txt not found under {os.path.dirname(p) or p} — the "
            f"pretrained tokenizer requires the published WordPiece vocab "
            f"(reference ships it as the VOCAB resource, BertResources.java)")
    with open(p, encoding="utf-8") as f:
        return [line.rstrip("\n") for line in f]


# ---------------------------------------------------------------------------
# HF-name tensors -> TransformerEncoder param tree
# ---------------------------------------------------------------------------


def _strip_prefix(raw: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    out = {}
    for k, v in raw.items():
        if k.startswith("bert."):
            k = k[len("bert."):]
        out[k] = v
    return out


class _W:
    """Name-indexed tensor store with (in,out)-orientation handling."""

    def __init__(self, raw: Dict[str, np.ndarray]):
        self.raw = _strip_prefix(raw)

    def dense(self, prefix: str) -> Tuple[np.ndarray, np.ndarray]:
        """Returns (kernel (in,out), bias)."""
        if prefix + ".weight_t" in self.raw:  # already (in, out)
            k = self.raw[prefix + ".weight_t"]
        else:
            k = self.raw[prefix + ".weight"].T  # torch (out, in)
        b = self.raw[prefix + ".bias"]
        return np.ascontiguousarray(k, np.float32), b.astype(np.float32)

    def ln(self, prefix: str) -> Dict[str, np.ndarray]:
        return {"scale": self.raw[prefix + ".weight"].astype(np.float32),
                "bias": self.raw[prefix + ".bias"].astype(np.float32)}

    def emb(self, name: str) -> np.ndarray:
        return self.raw[name + ".weight"].astype(np.float32)

    def has(self, name: str) -> bool:
        return any(k.startswith(name) for k in self.raw)


def bert_tree_from_hf(raw: Dict[str, np.ndarray],
                      num_layers: int) -> Dict[str, Any]:
    """Build the ``TransformerEncoder`` encoder subtree (no head) from
    HF-style named tensors. qkv is fused into the DenseGeneral layout
    (kernel (hidden, 3, heads*dim), bias (3, heads*dim))."""
    w = _W(raw)
    tree: Dict[str, Any] = {
        "tok_emb": {"embedding": w.emb("embeddings.word_embeddings")},
        "pos_emb": {"embedding": w.emb("embeddings.position_embeddings")},
        "ln_emb": w.ln("embeddings.LayerNorm"),
    }
    if w.has("embeddings.token_type_embeddings"):
        tree["type_emb"] = {
            "embedding": w.emb("embeddings.token_type_embeddings")}
    hidden = tree["tok_emb"]["embedding"].shape[1]
    for i in range(num_layers):
        p = f"encoder.layer.{i}."
        qk, qb = w.dense(p + "attention.self.query")
        kk, kb = w.dense(p + "attention.self.key")
        vk, vb = w.dense(p + "attention.self.value")
        ok, ob = w.dense(p + "attention.output.dense")
        ik, ib = w.dense(p + "intermediate.dense")
        mk, mb = w.dense(p + "output.dense")
        tree[f"layer_{i}"] = {
            "attention": {
                "qkv": {
                    "kernel": np.stack([qk, kk, vk], axis=1),  # (h, 3, h)
                    "bias": np.stack([qb, kb, vb], axis=0),    # (3, h)
                },
                "out": {"kernel": ok, "bias": ob},
            },
            "ln_att": w.ln(p + "attention.output.LayerNorm"),
            "mlp_in": {"kernel": ik, "bias": ib},
            "mlp_out": {"kernel": mk, "bias": mb},
            "ln_mlp": w.ln(p + "output.LayerNorm"),
        }
        assert tree[f"layer_{i}"]["attention"]["qkv"]["kernel"].shape[0] == hidden
    if w.has("pooler.dense"):
        pk, pb = w.dense("pooler.dense")
        tree["pooler"] = {"kernel": pk, "bias": pb}
    return tree


def load_bert_checkpoint(path: str):
    """Read a checkpoint directory -> (config_dict, encoder_subtree).

    ``config_dict`` carries the architecture (hidden_size, num_layers, ...)
    with HF/google key names normalised to :class:`BertConfig` fields."""
    fmt = _detect_format(path)
    if fmt is None:
        raise AkPluginNotExistException(
            f"no BERT checkpoint found under {path} (looked for "
            f"model.safetensors / pytorch_model.bin / flax_model.msgpack / "
            f"*.ckpt.index)")
    hf_cfg = _load_config(path)
    cfg = {
        "vocab_size": hf_cfg["vocab_size"],
        "hidden_size": hf_cfg["hidden_size"],
        "num_layers": hf_cfg.get("num_hidden_layers", hf_cfg.get("num_layers")),
        "num_heads": hf_cfg.get("num_attention_heads", hf_cfg.get("num_heads")),
        "intermediate_size": hf_cfg["intermediate_size"],
        "max_position": hf_cfg.get("max_position_embeddings", 512),
        "type_vocab_size": hf_cfg.get("type_vocab_size", 2),
        "do_lower_case": _infer_do_lower_case(path, hf_cfg),
    }
    reader = {
        "safetensors": lambda p: _read_safetensors(
            os.path.join(p, "model.safetensors")),
        "torch": lambda p: _read_torch_bin(os.path.join(p, "pytorch_model.bin")),
        "flax": lambda p: _read_flax_msgpack(
            os.path.join(p, "flax_model.msgpack")),
        "tf_ckpt": _read_tf_ckpt_dir,
    }[fmt]
    tree = bert_tree_from_hf(reader(path), cfg["num_layers"])
    return cfg, tree


def _read_tf_ckpt_dir(path: str) -> Dict[str, np.ndarray]:
    for f in sorted(os.listdir(path)):
        if f.endswith(".ckpt.index"):
            return _read_tf_ckpt(os.path.join(path, f[: -len(".index")]))
    raise AkPluginNotExistException(f"no *.ckpt.index under {path}")


def init_from_pretrained(model, cfg, subtree: Dict[str, Any], sample: dict,
                         seed: int = 0):
    """model.init with the encoder subtree grafted in; head (and any part the
    checkpoint lacks, e.g. pooler in some exports) keeps its fresh init."""
    import warnings

    import jax

    template = model.init(jax.random.PRNGKey(seed), **sample)
    params = dict(template["params"])
    skipped: list = []
    merged = _merge(params, subtree, skipped=skipped)
    if skipped:
        # silently dropping checkpoint tensors would leave layers at random
        # init and "fine-tuning" would quietly train from scratch
        warnings.warn(
            f"pretrained checkpoint tensors not consumed by the model "
            f"(left at fresh init): {skipped[:8]}"
            f"{' ...' if len(skipped) > 8 else ''}")
    return {**template, "params": merged}


def _merge(template: Dict[str, Any], new: Dict[str, Any], *, skipped: list,
           prefix: str = "") -> Dict[str, Any]:
    out = dict(template)
    for k, v in new.items():
        if k not in out:
            skipped.append(prefix + k)
            continue
        if isinstance(v, dict) and isinstance(out[k], dict):
            out[k] = _merge(out[k], v, skipped=skipped, prefix=prefix + k + ".")
        else:
            tv = out[k]
            if tuple(np.shape(tv)) != tuple(np.shape(v)):
                raise AkIllegalArgumentException(
                    f"pretrained tensor {k} has shape {np.shape(v)}, model "
                    f"expects {tuple(np.shape(tv))} — config mismatch")
            out[k] = np.asarray(v, np.float32)
    return out


# ---------------------------------------------------------------------------
# export (round-trip): params -> HF-layout directory
# ---------------------------------------------------------------------------


def save_bert_checkpoint(params, cfg, path: str, vocab: "list[str]") -> None:
    """Write an HF-layout checkpoint (config.json + model.safetensors +
    vocab.txt) from a TransformerEncoder param tree, so models trained here
    can be re-ingested (and shipped to other BERT stacks)."""
    os.makedirs(path, exist_ok=True)
    p = params.get("params", params)
    tensors: Dict[str, np.ndarray] = {}

    def dense_out(prefix: str, sub):  # to torch (out, in)
        tensors[prefix + ".weight"] = np.ascontiguousarray(
            np.asarray(sub["kernel"], np.float32).T)
        tensors[prefix + ".bias"] = np.asarray(sub["bias"], np.float32)

    def ln_out(prefix: str, sub):
        tensors[prefix + ".weight"] = np.asarray(sub["scale"], np.float32)
        tensors[prefix + ".bias"] = np.asarray(sub["bias"], np.float32)

    tensors["bert.embeddings.word_embeddings.weight"] = np.asarray(
        p["tok_emb"]["embedding"], np.float32)
    tensors["bert.embeddings.position_embeddings.weight"] = np.asarray(
        p["pos_emb"]["embedding"], np.float32)
    if "type_emb" in p:
        tensors["bert.embeddings.token_type_embeddings.weight"] = np.asarray(
            p["type_emb"]["embedding"], np.float32)
    ln_out("bert.embeddings.LayerNorm", p["ln_emb"])
    n_layers = cfg.num_layers if hasattr(cfg, "num_layers") else cfg["num_layers"]
    for i in range(n_layers):
        lp = p[f"layer_{i}"]
        hfp = f"bert.encoder.layer.{i}."
        qkv_k = np.asarray(lp["attention"]["qkv"]["kernel"], np.float32)
        qkv_b = np.asarray(lp["attention"]["qkv"]["bias"], np.float32)
        for j, nm in enumerate(("query", "key", "value")):
            tensors[hfp + f"attention.self.{nm}.weight"] = (
                np.ascontiguousarray(qkv_k[:, j, :].T))
            tensors[hfp + f"attention.self.{nm}.bias"] = qkv_b[j]
        dense_out(hfp + "attention.output.dense", lp["attention"]["out"])
        ln_out(hfp + "attention.output.LayerNorm", lp["ln_att"])
        dense_out(hfp + "intermediate.dense", lp["mlp_in"])
        dense_out(hfp + "output.dense", lp["mlp_out"])
        ln_out(hfp + "output.LayerNorm", lp["ln_mlp"])
    if "pooler" in p:
        dense_out("bert.pooler.dense", p["pooler"])

    _write_safetensors(os.path.join(path, "model.safetensors"), tensors)
    c = cfg if isinstance(cfg, dict) else {
        "vocab_size": cfg.vocab_size, "hidden_size": cfg.hidden_size,
        "num_layers": cfg.num_layers, "num_heads": cfg.num_heads,
        "intermediate_size": cfg.intermediate_size,
        "max_position": cfg.max_position,
        "type_vocab_size": cfg.type_vocab_size,
    }
    hf_cfg = {
        "model_type": "bert",
        "vocab_size": c["vocab_size"],
        "hidden_size": c["hidden_size"],
        "num_hidden_layers": c["num_layers"],
        "num_attention_heads": c["num_heads"],
        "intermediate_size": c["intermediate_size"],
        "max_position_embeddings": c["max_position"],
        "type_vocab_size": c.get("type_vocab_size", 2),
    }
    with open(os.path.join(path, "config.json"), "w") as f:
        json.dump(hf_cfg, f, indent=1)
    with open(os.path.join(path, "vocab.txt"), "w", encoding="utf-8") as f:
        f.write("\n".join(vocab) + "\n")


def _write_safetensors(path: str, tensors: Dict[str, np.ndarray]) -> None:
    _DT = {np.dtype(np.float32): "F32", np.dtype(np.float64): "F64",
           np.dtype(np.int64): "I64", np.dtype(np.int32): "I32"}
    header: Dict[str, Any] = {}
    off = 0
    bufs = []
    for name in sorted(tensors):
        a = np.ascontiguousarray(tensors[name])
        raw = a.tobytes()
        header[name] = {"dtype": _DT[a.dtype], "shape": list(a.shape),
                        "data_offsets": [off, off + len(raw)]}
        off += len(raw)
        bufs.append(raw)
    hb = json.dumps(header).encode()
    with open(path, "wb") as f:
        f.write(struct.pack("<Q", len(hb)))
        f.write(hb)
        for b in bufs:
            f.write(b)

"""Online serving tier — concurrent request router with dynamic micro-batching.

The production front end over :class:`~alink_tpu.pipeline.LocalPredictor`:
concurrent predict requests are queued per loaded model and a batcher thread
coalesces them into micro-batches sized onto the shape-bucket ladder
(``common/jitcache.py``), so sustained load rides already-compiled programs
with zero traces; per-row results scatter back to callers under per-request
deadlines. Admission control sheds load past a bounded queue's high-water
mark, a per-model circuit breaker degrades a failing model to fast rejects,
and the whole path is instrumented with ``serving.*`` spans, histograms, and
counters exported at ``GET /metrics``.
"""

from .router import (  # noqa: F401
    ModelServer,
    PredictFuture,
    ServingConfig,
    default_server,
    serving_bucket_ladder,
    serving_summary,
)
from .warmup_store import (  # noqa: F401
    load_warmup_spec,
    save_warmup_spec,
    warmup_sidecar_path,
)

from ..common.exceptions import (  # noqa: F401
    AkDeadlineExceededException,
    AkServingOverloadException,
)

"""Generic mapper-wrapping batch operators.

Capability parity with reference operator/batch/utils/ModelMapBatchOp.java:62
(model broadcast at :64,175) and MapBatchOp.java. The model "broadcast" is
trivial here — the mapper loads the model MTable once and the batched jit
kernel is replicated by XLA as needed.
"""

from __future__ import annotations

from typing import Type

from ...common.mtable import MTable
from ..base import AlgoOperator
from .base import BatchOperator


class MapBatchOp(BatchOperator):
    """Wrap a stateless Mapper class as an operator."""

    _min_inputs = 1
    _max_inputs = 1

    mapper_cls: Type = None

    def __init__(self, params=None, **kwargs):
        super().__init__(params, **kwargs)

    def _make_mapper(self, data_schema):
        return self.mapper_cls(data_schema, self.get_params())

    def _execute_impl(self, t: MTable) -> MTable:
        return self._make_mapper(t.schema).map_table(t)


class ModelMapBatchOp(BatchOperator):
    """Wrap a ModelMapper class; ``link_from(model_op, data_op)``."""

    _min_inputs = 2
    _max_inputs = 2

    mapper_cls: Type = None

    def __init__(self, params=None, **kwargs):
        super().__init__(params, **kwargs)

    def _make_mapper(self, model_schema, data_schema):
        return self.mapper_cls(model_schema, data_schema, self.get_params())

    def _execute_impl(self, model: MTable, t: MTable) -> MTable:
        mapper = self._make_mapper(model.schema, t.schema)
        mapper.load_model(model)
        return mapper.map_table(t)

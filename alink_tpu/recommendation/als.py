"""Alternating Least Squares matrix factorization — TPU-first.

Capability parity with the reference's block ALS (reference:
core/src/main/java/com/alibaba/alink/operator/common/recommendation/
HugeMfAlsImpl.java:326 — block-partitioned alternating sweeps; normal
equations per user/item block at :409-438; implicit-preference variant per
Hu/Koren/Volinsky).

TPU re-design: instead of Flink block shuffles, each half-sweep is ONE
compiled shard_map program. Ratings are laid out as padded per-entity
neighbor lists (ragged → rectangular, the XLA-friendly shape): for every
user a fixed-width row of rated item ids + ratings + mask. A sweep gathers
the (replicated) opposite-side factors, builds every k×k Gramian with one
einsum (MXU), adds λI, and solves all systems batched; the updated factors
are re-replicated with an all_gather. The implicit variant adds the shared
Y^T Y Gramian (computed once per sweep) and confidence weights c = 1 + α r.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import numpy as np

from ..parallel.mesh import AXIS_DATA, default_mesh, pad_to_multiple
from ..parallel.shardmap import shard_map


@dataclass
class AlsModelData:
    user_ids: np.ndarray     # original user id values (n_users,)
    item_ids: np.ndarray     # original item id values (n_items,)
    user_factors: np.ndarray  # (n_users, k) float32
    item_factors: np.ndarray  # (n_items, k) float32


def _pad_lists(idx_of: Dict[int, list], count: int) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Ragged neighbor lists → (ids, ratings, mask) rectangles."""
    max_deg = max((len(v) for v in idx_of.values()), default=1)
    max_deg = max(max_deg, 1)
    ids = np.zeros((count, max_deg), np.int32)
    rts = np.zeros((count, max_deg), np.float32)
    mask = np.zeros((count, max_deg), np.float32)
    for e, pairs in idx_of.items():
        d = len(pairs)
        if d:
            ids[e, :d] = [p[0] for p in pairs]
            rts[e, :d] = [p[1] for p in pairs]
            mask[e, :d] = 1.0
    return ids, rts, mask


def _half_sweep_fn(mesh, k: int, lam: float, implicit: bool, alpha: float):
    """Compiled half-sweep: solve all 'left' factors given 'right' factors."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    axis = AXIS_DATA

    def body(ids, rts, mask, cnt, right):
        # ids/rts/mask: (n_local, D); right: (m, k) replicated
        V = right[ids]                                  # (n_local, D, k)
        Vm = V * mask[..., None]
        if implicit:
            # A_u = Y^T Y + α Σ r_ui v v^T + λI ; b_u = Σ (1+α r) p v, p=1
            yty = right.T @ right                       # (k, k), replicated
            conf = alpha * rts * mask                   # c-1
            A = jnp.einsum("udk,udl->ukl", Vm * conf[..., None], V)
            A = A + yty[None] + lam * cnt[:, None, None] * jnp.eye(k)
            b = jnp.einsum("udk,ud->uk", Vm, (1.0 + conf) * mask)
        else:
            A = jnp.einsum("udk,udl->ukl", Vm, V)
            A = A + lam * jnp.maximum(cnt, 1.0)[:, None, None] * jnp.eye(k)
            b = jnp.einsum("udk,ud->uk", Vm, rts * mask)
        sol = jnp.linalg.solve(A, b[..., None])[..., 0]  # batched k×k solves
        return jnp.where(cnt[:, None] > 0, sol, 0.0)

    return jax.jit(
        shard_map(
            body, mesh=mesh,
            in_specs=(P(axis), P(axis), P(axis), P(axis), P()),
            out_specs=P(axis), check_vma=False,
        )
    )


def train_als(
    users: np.ndarray,
    items: np.ndarray,
    ratings: np.ndarray,
    *,
    rank: int = 10,
    num_iter: int = 10,
    lam: float = 0.1,
    implicit: bool = False,
    alpha: float = 40.0,
    seed: int = 0,
    max_neighbors: int = 0,
    mesh=None,
) -> AlsModelData:
    """Factorize sparse (user, item, rating) triples. λ is scaled by each
    entity's rating count (ALS-WR weighting, matching the reference).

    ``max_neighbors > 0`` caps each entity's padded neighbor list by random
    subsampling — the hot-point strategy: one viral item/user otherwise sets
    the rectangle width D for EVERY row of the sweep (reference:
    AlsForHotPointTrainBatchOp.java / MfAlsForHotPointBatchOp.java handle
    the same skew with a dedicated hub-block path)."""
    mesh = mesh or default_mesh()
    dp = mesh.shape[AXIS_DATA]

    u_ids, u_inv = np.unique(users, return_inverse=True)
    i_ids, i_inv = np.unique(items, return_inverse=True)
    n_u, n_i = len(u_ids), len(i_ids)
    r = np.asarray(ratings, np.float32)

    by_user: Dict[int, list] = {u: [] for u in range(n_u)}
    by_item: Dict[int, list] = {i: [] for i in range(n_i)}
    for u, i, v in zip(u_inv, i_inv, r):
        by_user[u].append((i, v))
        by_item[i].append((u, v))

    if max_neighbors and max_neighbors > 0:
        cap_rng = np.random.default_rng(seed + 1)
        for table in (by_user, by_item):
            for e, pairs in table.items():
                if len(pairs) > max_neighbors:
                    pick = cap_rng.choice(len(pairs), max_neighbors,
                                          replace=False)
                    table[e] = [pairs[j] for j in pick]

    uids, urts, umask = _pad_lists(by_user, n_u)
    iids, irts, imask = _pad_lists(by_item, n_i)
    ucnt = umask.sum(1)
    icnt = imask.sum(1)

    rng = np.random.default_rng(seed)
    scale = 1.0 / np.sqrt(rank)
    U = (rng.standard_normal((n_u, rank)) * scale).astype(np.float32)
    V = (rng.standard_normal((n_i, rank)) * scale).astype(np.float32)

    sweep = _half_sweep_fn(mesh, rank, lam, implicit, alpha)

    def pad(arr):
        n = arr.shape[0]
        np_ = pad_to_multiple(max(n, dp), dp)
        if np_ != n:
            arr = np.pad(arr, [(0, np_ - n)] + [(0, 0)] * (arr.ndim - 1))
        return arr

    u_in = [pad(x) for x in (uids, urts, umask, ucnt)]
    i_in = [pad(x) for x in (iids, irts, imask, icnt)]

    import jax

    for _ in range(num_iter):
        U = np.asarray(jax.device_get(sweep(*u_in, V)))[:n_u]
        V = np.asarray(jax.device_get(sweep(*i_in, U)))[:n_i]

    return AlsModelData(u_ids, i_ids, U, V)

"""Model ingestion: ONNX / torch.export / StableHLO → XLA-compiled inference.

The reference serves foreign models through three JVM plugin engines
(reference: dl_predictors/predictor-tf (SavedModelBundle), predictor-onnx
(OnnxRuntime), predictor-torch (libtorch TorchScript), behind the
DLPredictorService SPI at core/.../common/dl/plugin/DLPredictorService.java).
This package is the TPU-native equivalent: each format is *imported* into a
single jit-compiled XLA program instead of bridged to a foreign runtime.
"""

from .proto import OnnxGraph, OnnxModel, NodeProto, TensorProto, ValueInfo
from .convert import OnnxToJax, load_onnx_fn
from .torchfx import TorchToJax, load_torch_fn

__all__ = [
    "OnnxGraph", "OnnxModel", "NodeProto", "TensorProto", "ValueInfo",
    "OnnxToJax", "load_onnx_fn", "TorchToJax", "load_torch_fn",
]

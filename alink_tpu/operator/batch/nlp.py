"""NLP breadth: segmentation, n-grams, stop words, word counts, TF-IDF,
count vectorizer, keyword extraction.

Capability parity with the reference nlp package (reference:
core/src/main/java/com/alibaba/alink/operator/batch/nlp/
SegmentBatchOp.java (jieba-style dict DP; dict resource
core/src/main/resources/prob_emit.txt), NGramBatchOp.java,
StopWordsRemoverBatchOp.java (common/nlp/StopWordsRemoverMapper),
WordCountBatchOp.java, DocWordCountBatchOp.java, TfidfBatchOp.java,
DocCountVectorizerTrainBatchOp.java + common/nlp/DocCountVectorizerModelMapper
(featureType TF/IDF/TF_IDF/BINARY/WORD_COUNT),
KeywordsExtractionBatchOp.java (TextRank over a word graph)).

Re-design notes: the count-vectorizer serving path emits SparseVector blocks;
TextRank rides the graph engine's PageRank kernel (graph/engine.py) — the
word co-occurrence graph is just another edge list.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional

import numpy as np

from ...common.exceptions import AkIllegalArgumentException
from ...common.linalg import SparseVector
from ...common.model import model_to_table, table_to_model
from ...common.mtable import AlinkTypes, MTable, TableSchema
from ...common.params import InValidator, MinValidator, ParamInfo
from ...mapper import (
    HasOutputCol,
    HasReservedCols,
    HasSelectedCol,
    Mapper,
    ModelMapper,
    SISOMapper,
)
from .base import BatchOperator
from .utils import MapBatchOp, ModelMapBatchOp, ModelTrainOpMixin

# A minimal English stop-word list (reference ships resource files under
# core/src/main/resources; the op accepts a user list for anything else).
_DEFAULT_STOP_WORDS = frozenset("""
a an and are as at be by for from has he in is it its of on that the to was
were will with this these those i you your we they them their or not no but
if then else when while do does did done been being am
""".split())


class SegmentMapper(SISOMapper):
    """Dictionary unigram-DP segmentation (the jieba DAG-route algorithm
    without the HMM tail; reference: common/nlp/SegmentMapper.java). Words
    absent from the dictionary fall back to single characters."""

    USER_DEFINED_DICT = ParamInfo("userDefinedDict", list)

    def _dict(self) -> Dict[str, float]:
        words = self.get(self.USER_DEFINED_DICT) or []
        freq = {w: 10.0 for w in words}
        return freq

    def map_column(self, values, type_tag):
        return (np.asarray([self._segment(v) for v in values], object),
                AlinkTypes.STRING)

    def _segment(self, value):
        if value is None:
            return None
        text = str(value)
        freq = getattr(self, "_freq", None)
        if freq is None:
            freq = self._dict()
            self._freq = freq
            self._maxlen = max((len(w) for w in freq), default=1)
        n = len(text)
        if n == 0:
            return ""
        # DP over best log-prob split; unknown single chars get a low score
        best = [-1e18] * (n + 1)
        back = [0] * (n + 1)
        best[0] = 0.0
        total = sum(freq.values()) + 1.0
        for i in range(n):
            if best[i] == -1e18:
                continue
            for j in range(i + 1, min(n, i + self._maxlen) + 1):
                w = text[i:j]
                if j == i + 1:
                    score = math.log(freq.get(w, 0.5) / total)
                elif w in freq:
                    score = math.log(freq[w] / total)
                else:
                    continue
                if best[i] + score > best[j]:
                    best[j] = best[i] + score
                    back[j] = i
        toks = []
        j = n
        while j > 0:
            i = back[j]
            toks.append(text[i:j])
            j = i
        return " ".join(reversed(toks))


class SegmentBatchOp(MapBatchOp, HasSelectedCol, HasOutputCol,
                     HasReservedCols):
    mapper_cls = SegmentMapper
    USER_DEFINED_DICT = SegmentMapper.USER_DEFINED_DICT


class NGramMapper(SISOMapper):
    """word n-grams joined by '_' (reference: common/nlp/NGramMapper.java)."""

    N = ParamInfo("n", int, default=2, validator=MinValidator(1))

    def map_column(self, values, type_tag):
        n = int(self.get(self.N))

        def one(value):
            if value is None:
                return None
            toks = str(value).split()
            return " ".join("_".join(toks[i:i + n])
                            for i in range(max(len(toks) - n + 1, 0)))

        return np.asarray([one(v) for v in values], object), AlinkTypes.STRING


class NGramBatchOp(MapBatchOp, HasSelectedCol, HasOutputCol, HasReservedCols):
    mapper_cls = NGramMapper
    N = NGramMapper.N


class StopWordsRemoverMapper(SISOMapper):
    """(reference: common/nlp/StopWordsRemoverMapper.java)"""

    STOP_WORDS = ParamInfo("stopWords", list)
    CASE_SENSITIVE = ParamInfo("caseSensitive", bool, default=False)

    def map_column(self, values, type_tag):
        extra = self.get(self.STOP_WORDS) or []
        case = self.get(self.CASE_SENSITIVE)
        stop = set(_DEFAULT_STOP_WORDS) | (
            set(extra) if case else {w.lower() for w in extra})

        def one(value):
            if value is None:
                return None
            kept = [t for t in str(value).split()
                    if (t if case else t.lower()) not in stop]
            return " ".join(kept)

        return np.asarray([one(v) for v in values], object), AlinkTypes.STRING


class StopWordsRemoverBatchOp(MapBatchOp, HasSelectedCol, HasOutputCol,
                              HasReservedCols):
    mapper_cls = StopWordsRemoverMapper
    STOP_WORDS = StopWordsRemoverMapper.STOP_WORDS
    CASE_SENSITIVE = StopWordsRemoverMapper.CASE_SENSITIVE


_WORD_COUNT_SCHEMA = TableSchema(["word", "cnt"],
                                 [AlinkTypes.STRING, AlinkTypes.LONG])


class WordCountBatchOp(BatchOperator, HasSelectedCol):
    """Corpus word counts (reference: WordCountBatchOp.java)."""

    _min_inputs = 1
    _max_inputs = 1

    def _execute_impl(self, t: MTable) -> MTable:
        from collections import Counter

        counter = Counter()
        for doc in t.col(self.get(HasSelectedCol.SELECTED_COL)):
            if doc is not None:
                counter.update(str(doc).split())
        items = counter.most_common()
        return MTable(
            {"word": np.asarray([w for w, _ in items], object),
             "cnt": np.asarray([c for _, c in items], np.int64)},
            _WORD_COUNT_SCHEMA)

    def _out_schema(self, in_schema):
        return _WORD_COUNT_SCHEMA


_DOC_WC_SCHEMA = TableSchema(["docId", "word", "cnt"],
                             [AlinkTypes.STRING, AlinkTypes.STRING,
                              AlinkTypes.LONG])


class DocWordCountBatchOp(BatchOperator):
    """(docId, word, cnt) triples (reference: DocWordCountBatchOp.java)."""

    DOC_ID_COL = ParamInfo("docIdCol", str, optional=False)
    CONTENT_COL = ParamInfo("contentCol", str, optional=False,
                            aliases=("selectedCol",))

    _min_inputs = 1
    _max_inputs = 1

    def _execute_impl(self, t: MTable) -> MTable:
        from collections import Counter

        rows = []
        for did, doc in zip(t.col(self.get(self.DOC_ID_COL)),
                            t.col(self.get(self.CONTENT_COL))):
            counter = Counter(str(doc).split() if doc is not None else [])
            for w, c in counter.items():
                rows.append((str(did), w, c))
        return MTable.from_rows(rows, _DOC_WC_SCHEMA)

    def _out_schema(self, in_schema):
        return _DOC_WC_SCHEMA


_TFIDF_SCHEMA = TableSchema(
    ["docId", "word", "cnt", "tf", "idf", "tfidf"],
    [AlinkTypes.STRING, AlinkTypes.STRING, AlinkTypes.LONG,
     AlinkTypes.DOUBLE, AlinkTypes.DOUBLE, AlinkTypes.DOUBLE])


class TfidfBatchOp(BatchOperator):
    """TF-IDF from (docId, word, cnt) triples — chain after DocWordCount
    (reference: TfidfBatchOp.java)."""

    DOC_ID_COL = ParamInfo("docIdCol", str, default="docId")
    WORD_COL = ParamInfo("wordCol", str, default="word")
    COUNT_COL = ParamInfo("countCol", str, default="cnt")

    _min_inputs = 1
    _max_inputs = 1

    def _execute_impl(self, t: MTable) -> MTable:
        dids = np.asarray(t.col(self.get(self.DOC_ID_COL)), object).astype(str)
        words = np.asarray(t.col(self.get(self.WORD_COL)), object).astype(str)
        cnts = np.asarray(t.col(self.get(self.COUNT_COL)), np.float64)
        doc_total: Dict[str, float] = {}
        doc_freq: Dict[str, int] = {}
        for d, w, c in zip(dids, words, cnts):
            doc_total[d] = doc_total.get(d, 0.0) + c
            doc_freq[w] = doc_freq.get(w, 0) + 1
        n_docs = len(doc_total)
        rows = []
        for d, w, c in zip(dids, words, cnts):
            tf = c / doc_total[d]
            idf = math.log((1.0 + n_docs) / (1.0 + doc_freq[w]))
            rows.append((d, w, int(c), tf, idf, tf * idf))
        return MTable.from_rows(rows, _TFIDF_SCHEMA)

    def _out_schema(self, in_schema):
        return _TFIDF_SCHEMA


class DocCountVectorizerTrainBatchOp(ModelTrainOpMixin, BatchOperator,
                                     HasSelectedCol):
    """Vocabulary + document frequencies (reference:
    DocCountVectorizerTrainBatchOp.java)."""

    MAX_DF = ParamInfo("maxDF", float, default=1.0)
    MIN_DF = ParamInfo("minDF", float, default=1.0)
    VOCAB_SIZE = ParamInfo("vocabSize", int, default=1 << 18)

    _min_inputs = 1
    _max_inputs = 1

    def _execute_impl(self, t: MTable) -> MTable:
        from collections import Counter

        docs = [str(v).split() if v is not None else []
                for v in t.col(self.get(HasSelectedCol.SELECTED_COL))]
        n_docs = max(len(docs), 1)
        df = Counter()
        for doc in docs:
            df.update(set(doc))
        min_df = self.get(self.MIN_DF)
        max_df = self.get(self.MAX_DF)
        min_abs = min_df if min_df >= 1 else min_df * n_docs
        max_abs = max_df if max_df > 1 else max_df * n_docs
        items = [(w, c) for w, c in df.most_common()
                 if min_abs <= c <= max_abs][:self.get(self.VOCAB_SIZE)]
        vocab = sorted(w for w, _ in items)
        dfs = {w: c for w, c in items}
        meta = {
            "modelName": "DocCountVectorizerModel",
            "selectedCol": self.get(HasSelectedCol.SELECTED_COL),
            "vocab": vocab,
            "docFreq": [dfs[w] for w in vocab],
            "numDocs": n_docs,
        }
        return model_to_table(meta, {})

    def _static_meta_keys(self, in_schema):
        return {"modelName": "DocCountVectorizerModel"}


class DocCountVectorizerModelMapper(ModelMapper, HasSelectedCol, HasOutputCol,
                                    HasReservedCols):
    """featureType TF / IDF / TF_IDF / BINARY / WORD_COUNT (reference:
    common/nlp/DocCountVectorizerModelMapper.java)."""

    FEATURE_TYPE = ParamInfo(
        "featureType", str, default="WORD_COUNT",
        validator=InValidator("TF", "IDF", "TF_IDF", "BINARY", "WORD_COUNT"))

    def load_model(self, model: MTable):
        self.meta, _ = table_to_model(model)
        self.w2i = {w: i for i, w in enumerate(self.meta["vocab"])}
        n_docs = self.meta["numDocs"]
        self.idf = np.asarray(
            [math.log((1.0 + n_docs) / (1.0 + c))
             for c in self.meta["docFreq"]], np.float64)
        return self

    def output_schema(self, input_schema):
        out = self.get(HasOutputCol.OUTPUT_COL) or "vec"
        return self._append_result_schema(input_schema, [out],
                                          [AlinkTypes.SPARSE_VECTOR])

    def map_table(self, t: MTable) -> MTable:
        from collections import Counter

        out = self.get(HasOutputCol.OUTPUT_COL) or "vec"
        col = self.get(HasSelectedCol.SELECTED_COL) or self.meta["selectedCol"]
        ftype = self.get(self.FEATURE_TYPE)
        V = len(self.w2i)
        vecs = []
        for doc in t.col(col):
            counter = Counter(str(doc).split() if doc is not None else [])
            idx, vals = [], []
            total = sum(counter.values()) or 1
            for w, c in counter.items():
                j = self.w2i.get(w)
                if j is None:
                    continue
                if ftype == "WORD_COUNT":
                    v = float(c)
                elif ftype == "TF":
                    v = c / total
                elif ftype == "BINARY":
                    v = 1.0
                elif ftype == "IDF":
                    v = self.idf[j]
                else:  # TF_IDF
                    v = c / total * self.idf[j]
                idx.append(j)
                vals.append(v)
            vecs.append(SparseVector(V, idx, vals))
        return self._append_result(
            t, {out: np.asarray(vecs, object)}, {out: AlinkTypes.SPARSE_VECTOR})


class DocCountVectorizerPredictBatchOp(ModelMapBatchOp, HasSelectedCol,
                                       HasOutputCol, HasReservedCols):
    mapper_cls = DocCountVectorizerModelMapper
    FEATURE_TYPE = DocCountVectorizerModelMapper.FEATURE_TYPE


_KEYWORDS_SCHEMA = TableSchema(["docId", "keywords"],
                               [AlinkTypes.STRING, AlinkTypes.STRING])


class KeywordsExtractionBatchOp(BatchOperator):
    """TextRank keywords per document (reference:
    KeywordsExtractionBatchOp.java — TextRank over the word co-occurrence
    window graph, scored by the shared PageRank kernel)."""

    DOC_ID_COL = ParamInfo("docIdCol", str)
    SELECTED_COL = ParamInfo("selectedCol", str, optional=False)
    TOP_N = ParamInfo("topN", int, default=5, validator=MinValidator(1))
    WINDOW_SIZE = ParamInfo("windowSize", int, default=2,
                            validator=MinValidator(1))

    _min_inputs = 1
    _max_inputs = 1

    def _execute_impl(self, t: MTable) -> MTable:
        from ...graph.engine import MemoryGraph, pagerank

        id_col = self.get(self.DOC_ID_COL)
        topn = self.get(self.TOP_N)
        win = self.get(self.WINDOW_SIZE)
        doc_ids = (t.col(id_col) if id_col
                   else np.arange(t.num_rows).astype(str))
        rows = []
        for did, doc in zip(doc_ids, t.col(self.get(self.SELECTED_COL))):
            toks = [w for w in str(doc).split()
                    if w.lower() not in _DEFAULT_STOP_WORDS]
            uniq = sorted(set(toks))
            if not uniq:
                rows.append((str(did), ""))
                continue
            w2i = {w: i for i, w in enumerate(uniq)}
            src, dst = [], []
            for i, w in enumerate(toks):
                for j in range(i + 1, min(i + win + 1, len(toks))):
                    if toks[j] != w:
                        src.append(w2i[w])
                        dst.append(w2i[toks[j]])
            if not src:
                rows.append((str(did), " ".join(uniq[:topn])))
                continue
            src, dst = np.asarray(src + dst), np.asarray(dst + src)
            g = MemoryGraph(len(uniq), src, dst)
            pr = pagerank(g, max_iter=50)
            order = np.argsort(-pr)[:topn]
            rows.append((str(did), " ".join(uniq[i] for i in order)))
        return MTable.from_rows(rows, _KEYWORDS_SCHEMA)

    def _out_schema(self, in_schema):
        return _KEYWORDS_SCHEMA


class DocHashCountVectorizerModelMapper(ModelMapper, HasSelectedCol,
                                        HasOutputCol, HasReservedCols):
    """Hashing-trick doc vectorizer serving (reference:
    common/nlp/DocHashCountVectorizerModelMapper.java). Model carries the
    IDF table over hash slots."""

    FEATURE_TYPE = ParamInfo(
        "featureType", str, default="WORD_COUNT",
        validator=InValidator("TF", "IDF", "TF_IDF", "BINARY", "WORD_COUNT"))

    def load_model(self, model: MTable):
        self.meta, arrays = table_to_model(model)
        self.idf = arrays["idf"]
        return self

    def output_schema(self, input_schema):
        out = self.get(HasOutputCol.OUTPUT_COL) or "vec"
        return self._append_result_schema(input_schema, [out],
                                          [AlinkTypes.SPARSE_VECTOR])

    def map_table(self, t: MTable) -> MTable:
        from collections import Counter

        from .feature2 import _hash32

        out = self.get(HasOutputCol.OUTPUT_COL) or "vec"
        col = self.get(HasSelectedCol.SELECTED_COL) or self.meta["selectedCol"]
        ftype = self.get(self.FEATURE_TYPE)
        m = self.meta["numFeatures"]
        vecs = []
        for doc in t.col(col):
            counter = Counter(
                _hash32(w) % m
                for w in (str(doc).split() if doc is not None else []))
            total = sum(counter.values()) or 1
            idx, vals = [], []
            for slot, c in counter.items():
                if ftype == "WORD_COUNT":
                    v = float(c)
                elif ftype == "TF":
                    v = c / total
                elif ftype == "BINARY":
                    v = 1.0
                elif ftype == "IDF":
                    v = float(self.idf[slot])
                else:
                    v = c / total * float(self.idf[slot])
                idx.append(slot)
                vals.append(v)
            vecs.append(SparseVector(m, idx, vals))
        return self._append_result(
            t, {out: np.asarray(vecs, object)},
            {out: AlinkTypes.SPARSE_VECTOR})


class DocHashCountVectorizerTrainBatchOp(ModelTrainOpMixin, BatchOperator,
                                         HasSelectedCol):
    """(reference: DocHashCountVectorizerTrainBatchOp.java — IDF over hash
    slots, no vocabulary table)."""

    NUM_FEATURES = ParamInfo("numFeatures", int, default=1 << 18)

    _min_inputs = 1
    _max_inputs = 1

    def _execute_impl(self, t: MTable) -> MTable:
        from .feature2 import _hash32

        m = int(self.get(self.NUM_FEATURES))
        df = np.zeros(m, np.float64)
        docs = [str(v).split() if v is not None else []
                for v in t.col(self.get(HasSelectedCol.SELECTED_COL))]
        for doc in docs:
            for slot in {_hash32(w) % m for w in doc}:
                df[slot] += 1
        n_docs = max(len(docs), 1)
        idf = np.log((1.0 + n_docs) / (1.0 + df))
        meta = {"modelName": "DocHashCountVectorizerModel",
                "selectedCol": self.get(HasSelectedCol.SELECTED_COL),
                "numFeatures": m}
        return model_to_table(meta, {"idf": idf})

    def _static_meta_keys(self, in_schema):
        return {"modelName": "DocHashCountVectorizerModel",
                "numFeatures": self.get(self.NUM_FEATURES)}


class DocHashCountVectorizerPredictBatchOp(ModelMapBatchOp, HasSelectedCol,
                                           HasOutputCol, HasReservedCols):
    mapper_cls = DocHashCountVectorizerModelMapper
    FEATURE_TYPE = DocHashCountVectorizerModelMapper.FEATURE_TYPE


class TokenizerMapper(SISOMapper):
    """Lowercase whitespace tokenizer, space-joined output (reference:
    common/nlp/TokenizerMapper.java)."""

    def map_column(self, values, type_tag):
        out = []
        for v in values:
            out.append(None if v is None
                       else " ".join(str(v).lower().split()))
        return np.asarray(out, object), AlinkTypes.STRING


class TokenizerBatchOp(MapBatchOp, HasSelectedCol, HasOutputCol,
                       HasReservedCols):
    """(reference: operator/batch/nlp/TokenizerBatchOp.java)"""

    mapper_cls = TokenizerMapper


class RegexTokenizerMapper(SISOMapper):
    """Regex split (gaps=True) or regex match (gaps=False) tokenizer
    (reference: common/nlp/RegexTokenizerMapper.java)."""

    PATTERN = ParamInfo("pattern", str, default=r"\s+")
    GAPS = ParamInfo("gaps", bool, default=True)
    MIN_TOKEN_LENGTH = ParamInfo("minTokenLength", int, default=1)
    TO_LOWER_CASE = ParamInfo("toLowerCase", bool, default=True)

    def map_column(self, values, type_tag):
        import re as _re

        pat = _re.compile(self.get(self.PATTERN))
        gaps = self.get(self.GAPS)
        min_len = self.get(self.MIN_TOKEN_LENGTH)
        lower = self.get(self.TO_LOWER_CASE)
        out = []
        for v in values:
            if v is None:
                out.append(None)
                continue
            s = str(v).lower() if lower else str(v)
            toks = pat.split(s) if gaps else pat.findall(s)
            out.append(" ".join(t for t in toks if len(t) >= min_len))
        return np.asarray(out, object), AlinkTypes.STRING


class RegexTokenizerBatchOp(MapBatchOp, HasSelectedCol, HasOutputCol,
                            HasReservedCols):
    """(reference: operator/batch/nlp/RegexTokenizerBatchOp.java)"""

    mapper_cls = RegexTokenizerMapper
    PATTERN = RegexTokenizerMapper.PATTERN
    GAPS = RegexTokenizerMapper.GAPS
    MIN_TOKEN_LENGTH = RegexTokenizerMapper.MIN_TOKEN_LENGTH
    TO_LOWER_CASE = RegexTokenizerMapper.TO_LOWER_CASE

"""ALS recommendation quick-start (reference:
examples/src/main/java/com/alibaba/alink/ALSExample.java): train block-ALS
on ratings, then serve every recommender flavor — rate prediction,
items-per-user top-k, similar items — through the pipeline Recommender
stages."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

import alink_tpu.pipeline as P  # noqa: E402
from alink_tpu.common.mtable import MTable  # noqa: E402
from alink_tpu.operator.batch import AlsTrainBatchOp  # noqa: E402
from alink_tpu.operator.batch.base import TableSourceBatchOp  # noqa: E402


def main():
    rng = np.random.default_rng(0)
    # block preference structure: even users love even items
    users = np.repeat(np.arange(12), 8)
    items = np.tile(np.arange(8), 12)
    rates = np.where((users % 2) == (items % 2), 4.5, 1.0) \
        + 0.2 * rng.normal(size=len(users))
    ratings = MTable({"user": users.astype(np.int64),
                      "item": items.astype(np.int64), "rate": rates})

    model = AlsTrainBatchOp(
        userCol="user", itemCol="item", rateCol="rate", rank=8,
        numIter=15, lambda_=0.05,
    ).link_from(TableSourceBatchOp(ratings)).collect()

    # rate prediction
    rec = P.AlsRateRecommender(
        userCol="user", itemCol="item", predictionCol="score",
    ).set_model_data(model)
    q = MTable({"user": np.asarray([0, 0], np.int64),
                "item": np.asarray([2, 3], np.int64)})  # even vs odd item
    out = rec.transform(q).collect()
    s = np.asarray(out.col("score"))
    print(f"user 0: even item scores {s[0]:.2f}, odd item {s[1]:.2f}")
    assert s[0] > s[1] + 1.0

    # top-k items per user
    topk = P.AlsItemsPerUserRecommender(
        userCol="user", k=3, predictionCol="recs",
    ).set_model_data(model)
    recs = topk.transform(MTable({"user": np.asarray([1], np.int64)})).collect()
    print("user 1 top-3:", recs.col("recs")[0])


if __name__ == "__main__":
    main()

"""Tokenizer coverage on the SHIPPED real-text corpora (their first tier-1
consumers): vocab round-trips (list + file), deterministic vocab builds,
and deterministic batch shapes on data/reviews_unlabeled.txt and
data/sst2_mini.csv."""

import numpy as np
import pytest

from alink_tpu.dl.data import load_reviews, load_sst2, sst2_split
from alink_tpu.dl.tokenizer import CLS, PAD, SEP, Tokenizer

pytestmark = pytest.mark.training


# ---------------------------------------------------------------------------
# corpus loaders
# ---------------------------------------------------------------------------

def test_load_reviews_shape_and_content():
    texts = load_reviews()
    assert len(texts) == 4400
    assert all(isinstance(t, str) and t for t in texts)
    assert load_reviews(limit=16) == texts[:16]


def test_load_sst2_rows_and_labels():
    texts, y = load_sst2()
    assert len(texts) == len(y) > 400
    assert set(np.unique(y)) == {0, 1}
    # quoted commas must survive csv parsing as one text field
    assert all("\n" not in t for t in texts)
    # roughly balanced — the holdout accuracy metric is meaningful
    assert 0.3 < float(y.mean()) < 0.7


def test_sst2_split_deterministic_and_disjoint():
    tr1, try1, ho1, hoy1 = sst2_split(seed=0)
    tr2, try2, ho2, hoy2 = sst2_split(seed=0)
    assert tr1 == tr2 and ho1 == ho2
    assert np.array_equal(try1, try2) and np.array_equal(hoy1, hoy2)
    texts, _ = load_sst2()
    assert len(tr1) + len(ho1) == len(texts)
    assert len(ho1) == max(1, int(len(texts) * 0.2))


# ---------------------------------------------------------------------------
# vocab round-trips
# ---------------------------------------------------------------------------

def test_vocab_roundtrip_list_and_file(tmp_path):
    texts = load_reviews(limit=200)
    tok = Tokenizer.build(texts, vocab_size=500)
    sample = texts[:20]

    # list round-trip (the checkpoint path: save_bert_checkpoint stores
    # to_list(), fine-tune rebuilds via from_list)
    tok2 = Tokenizer.from_list(tok.to_list())
    assert tok2.vocab == tok.vocab
    for t in sample:
        assert tok2.tokenize(t) == tok.tokenize(t)

    # vocab.txt round-trip (the HF-layout file the BERT ops read)
    p = tmp_path / "vocab.txt"
    p.write_text("\n".join(tok.to_list()) + "\n", encoding="utf-8")
    tok3 = Tokenizer.from_vocab_file(str(p))
    assert tok3.vocab == tok.vocab
    for t in sample:
        assert tok3.encode(t, max_len=24) == tok.encode(t, max_len=24)


def test_vocab_build_deterministic():
    texts = load_reviews(limit=300)
    a = Tokenizer.build(texts, vocab_size=400)
    b = Tokenizer.build(texts, vocab_size=400)
    assert a.to_list() == b.to_list()


# ---------------------------------------------------------------------------
# deterministic batch shapes
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("max_len", [16, 32])
def test_encode_batch_shapes_on_corpora(max_len):
    sst_texts, _ = load_sst2()
    texts = sst_texts[:64] + load_reviews(limit=64)
    tok = Tokenizer.build(texts, vocab_size=600)
    enc = tok.encode_batch(texts, max_len=max_len)
    assert sorted(enc) == ["attention_mask", "input_ids", "token_type_ids"]
    for k, arr in enc.items():
        assert arr.shape == (len(texts), max_len), k
        assert arr.dtype == np.int32, k
    ids, mask = enc["input_ids"], enc["attention_mask"]
    assert set(np.unique(mask)) <= {0, 1}
    # layout: [CLS] first, ids outside the mask are all [PAD], real tokens
    # never exceed the vocab
    assert (ids[:, 0] == tok.vocab[CLS]).all()
    assert (ids[mask == 0] == tok.vocab[PAD]).all()
    assert ids.max() < tok.vocab_size
    # every row ends its masked span with [SEP] (truncation keeps it)
    last = mask.sum(axis=1) - 1
    assert (ids[np.arange(len(texts)), last] == tok.vocab[SEP]).all()
    # determinism: the same corpus encodes to the same blocks
    enc2 = tok.encode_batch(texts, max_len=max_len)
    for k in enc:
        assert np.array_equal(enc[k], enc2[k]), k

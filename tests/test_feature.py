"""Feature-engineering + dataproc breadth tests.

Mirrors the reference test style (reference: core/src/test/java/com/alibaba/
alink/operator/batch/feature/OneHotTrainBatchOpTest.java,
PcaTrainBatchOpTest.java, dataproc/StringIndexerTrainBatchOpTest.java, ...).
"""

import numpy as np
import pytest

from alink_tpu.operator.batch import (
    BinningPredictBatchOp,
    BinningTrainBatchOp,
    ChiSqSelectorBatchOp,
    ChiSqSelectorPredictBatchOp,
    EqualWidthDiscretizerPredictBatchOp,
    EqualWidthDiscretizerTrainBatchOp,
    FeatureHasherBatchOp,
    ImputerPredictBatchOp,
    ImputerTrainBatchOp,
    JsonValueBatchOp,
    LookupBatchOp,
    MaxAbsScalerPredictBatchOp,
    MaxAbsScalerTrainBatchOp,
    MemSourceBatchOp,
    OneHotPredictBatchOp,
    OneHotTrainBatchOp,
    PcaPredictBatchOp,
    PcaTrainBatchOp,
    QuantileDiscretizerPredictBatchOp,
    QuantileDiscretizerTrainBatchOp,
    StringIndexerPredictBatchOp,
    StringIndexerTrainBatchOp,
    TypeConvertBatchOp,
)
from alink_tpu.pipeline import OneHotEncoder, PCA, Pipeline, StringIndexer


def test_onehot_roundtrip():
    src = MemSourceBatchOp(
        [("a", "x"), ("b", "y"), ("a", "z")], "c1 string, c2 string")
    model = OneHotTrainBatchOp(selectedCols=["c1", "c2"], dropLast=False) \
        .link_from(src)
    out = OneHotPredictBatchOp(outputCol="vec").link_from(model, src).collect()
    vecs = list(out.col("vec"))
    # c1 has 2 tokens + invalid, c2 has 3 + invalid → total size 7
    assert vecs[0].n == 7
    assert set(vecs[0].indices.tolist()) == {0, 3}   # a→0, x→3 (offset 3)
    assert set(vecs[1].indices.tolist()) == {1, 4}


def test_onehot_drop_last_and_unseen():
    train = MemSourceBatchOp([("a",), ("b",), ("c",)], "c1 string")
    test = MemSourceBatchOp([("a",), ("c",), ("zz",)], "c1 string")
    model = OneHotTrainBatchOp(selectedCols=["c1"], dropLast=True) \
        .link_from(train)
    out = OneHotPredictBatchOp(outputCol="vec").link_from(model, test).collect()
    vecs = list(out.col("vec"))
    assert vecs[0].n == 3            # 2 real slots + invalid
    assert vecs[0].indices.tolist() == [0]
    assert vecs[1].indices.tolist() == []      # dropped last category
    assert vecs[2].indices.tolist() == [2]     # unseen → invalid slot


def test_pca_recovers_low_rank():
    rng = np.random.default_rng(0)
    z = rng.normal(size=(200, 2))
    W = rng.normal(size=(2, 5))
    X = z @ W + 0.01 * rng.normal(size=(200, 5))
    rows = [tuple(float(v) for v in row) for row in X]
    src = MemSourceBatchOp(rows, "a double, b double, c double, d double, e double")
    model_op = PcaTrainBatchOp(k=2, calculationType="COV").link_from(src)
    model_op.collect()
    out = PcaPredictBatchOp(outputCol="p").link_from(model_op, src).collect()
    P = np.stack([v.data for v in out.col("p")])
    assert P.shape == (200, 2)
    # 2 components explain ~all variance
    from alink_tpu.common.model import table_to_model
    meta, _ = table_to_model(model_op.collect())
    assert sum(meta["explainedVarianceRatio"]) > 0.99


def test_quantile_discretizer():
    rows = [(float(i),) for i in range(100)]
    src = MemSourceBatchOp(rows, "v double")
    model = QuantileDiscretizerTrainBatchOp(selectedCols=["v"], numBuckets=4) \
        .link_from(src)
    out = QuantileDiscretizerPredictBatchOp().link_from(model, src).collect()
    ids = np.asarray(out.col("v"))
    assert set(ids.tolist()) == {0, 1, 2, 3}
    counts = np.bincount(ids)
    assert all(abs(c - 25) <= 1 for c in counts)


def test_equal_width_discretizer():
    rows = [(0.0,), (2.5,), (5.0,), (7.5,), (10.0,)]
    src = MemSourceBatchOp(rows, "v double")
    model = EqualWidthDiscretizerTrainBatchOp(
        selectedCols=["v"], numBuckets=4).link_from(src)
    out = EqualWidthDiscretizerPredictBatchOp().link_from(model, src).collect()
    assert list(out.col("v")) == [0, 1, 2, 3, 3]


def test_binning_woe_sign():
    # feature>0.5 strongly predicts label "1"
    rng = np.random.default_rng(1)
    rows = []
    for _ in range(500):
        x = float(rng.random())
        label = "1" if (x > 0.5) == (rng.random() < 0.9) else "0"
        rows.append((x, label))
    src = MemSourceBatchOp(rows, "x double, label string")
    model = BinningTrainBatchOp(
        selectedCols=["x"], labelCol="label", numBuckets=2,
        positiveLabelValueString="1").link_from(src)
    from alink_tpu.common.model import table_to_model
    meta, _ = table_to_model(model.collect())
    woe = meta["woeMap"]["x"]
    assert woe[0] < 0 < woe[1]          # low bin anti-predicts, high bin predicts
    assert meta["ivMap"]["x"] > 0.5     # strong feature
    out = BinningPredictBatchOp(encode="WOE").link_from(model, src).collect()
    assert out.schema.type_of("x") == "DOUBLE"


def test_feature_hasher_deterministic():
    src = MemSourceBatchOp([("a", 1.5), ("b", 2.0), ("a", 1.5)],
                           "cat string, num double")
    out = FeatureHasherBatchOp(outputCol="h", numFeatures=64).link_from(src) \
        .collect()
    vecs = list(out.col("h"))
    assert vecs[0].n == 64
    assert (vecs[0].indices.tolist(), vecs[0].values.tolist()) == \
           (vecs[2].indices.tolist(), vecs[2].values.tolist())
    assert vecs[0].indices.tolist() != vecs[1].indices.tolist()


def test_chisq_selector():
    rng = np.random.default_rng(2)
    rows = []
    for _ in range(300):
        label = int(rng.integers(2))
        dep = float(label)                     # deterministic
        ind = float(rng.integers(2))           # independent
        rows.append((dep, ind, label))
    src = MemSourceBatchOp(rows, "dep double, ind double, label int")
    model = ChiSqSelectorBatchOp(
        selectedCols=["dep", "ind"], labelCol="label", numTopFeatures=1) \
        .link_from(src)
    out = ChiSqSelectorPredictBatchOp().link_from(model, src).collect()
    assert "dep" in out.names and "ind" not in out.names


def test_max_abs_scaler():
    src = MemSourceBatchOp([(-4.0,), (2.0,)], "v double")
    model = MaxAbsScalerTrainBatchOp(selectedCols=["v"]).link_from(src)
    out = MaxAbsScalerPredictBatchOp().link_from(model, src).collect()
    assert list(out.col("v")) == [-1.0, 0.5]


def test_string_indexer_orders_and_invalid():
    train = MemSourceBatchOp([("b",), ("a",), ("b",), ("c",), ("b",)],
                             "c string")
    test = MemSourceBatchOp([("a",), ("b",), ("zz",)], "c string")
    model = StringIndexerTrainBatchOp(
        selectedCols=["c"], stringOrderType="FREQUENCY_DESC").link_from(train)
    out = StringIndexerPredictBatchOp(handleInvalid="KEEP") \
        .link_from(model, test).collect()
    ids = list(out.col("c"))
    assert ids[1] == 0          # 'b' most frequent → id 0
    assert ids[2] == 3          # unseen → num_tokens
    assert out.schema.type_of("c") == "LONG"


def test_imputer_mean():
    src = MemSourceBatchOp([(1.0,), (float("nan"),), (3.0,)], "v double")
    model = ImputerTrainBatchOp(selectedCols=["v"], strategy="MEAN") \
        .link_from(src)
    out = ImputerPredictBatchOp().link_from(model, src).collect()
    assert list(out.col("v")) == [1.0, 2.0, 3.0]


def test_json_value():
    src = MemSourceBatchOp(
        [('{"a": {"b": 7}, "c": [1, 2]}',), ('{"a": {"b": 9}}',)],
        "js string")
    out = JsonValueBatchOp(
        selectedCol="js", jsonPath=["$.a.b", "$.c[0]"],
        outputCols=["ab", "c0"]).link_from(src).collect()
    assert list(out.col("ab")) == ["7", "9"]
    assert list(out.col("c0")) == ["1", None]


def test_lookup():
    dict_t = MemSourceBatchOp([("a", 10.0), ("b", 20.0)],
                              "k string, price double")
    data = MemSourceBatchOp([("a",), ("b",), ("q",)], "key string")
    out = LookupBatchOp(
        mapKeyCols=["k"], mapValueCols=["price"], selectedCols=["key"],
        outputCols=["price"]).link_from(dict_t, data).collect()
    prices = list(out.col("price"))
    assert prices[:2] == [10.0, 20.0]
    assert np.isnan(prices[2])  # numeric miss → NaN (DOUBLE column)


def test_type_convert():
    src = MemSourceBatchOp([(1.7, "x")], "v double, s string")
    out = TypeConvertBatchOp(selectedCols=["v"], targetType="LONG") \
        .link_from(src).collect()
    assert out.schema.type_of("v") == "LONG"
    assert list(out.col("v")) == [1]


def test_pipeline_with_new_stages():
    rng = np.random.default_rng(3)
    rows = [(("u" if rng.random() < 0.5 else "v"), float(rng.normal()),
             float(rng.normal())) for _ in range(50)]
    src = MemSourceBatchOp(rows, "cat string, x double, y double")
    pipe = Pipeline(
        StringIndexer(selectedCols=["cat"]),
        PCA(selectedCols=["x", "y"], k=1, outputCol="p"),
    )
    model = pipe.fit(src)
    out = model.transform(src).collect()
    assert out.schema.type_of("cat") == "LONG"
    assert "p" in out.names


def test_onehot_pipeline_estimator():
    src = MemSourceBatchOp([("a",), ("b",), ("a",)], "c string")
    model = OneHotEncoder(selectedCols=["c"], dropLast=False,
                          outputCol="v").fit(src)
    out = model.transform(src).collect()
    assert out.col("v")[0].n == 3


def test_directreader_bridges(tmp_path):
    from alink_tpu.io.ak import write_ak
    from alink_tpu.io.directreader import (DirectReader, LocalFileDataBridge,
                                           MemoryDataBridge)
    from alink_tpu.operator.batch import StandardScalerTrainBatchOp

    src = MemSourceBatchOp([(1.0,), (3.0,)], "v double")
    train = StandardScalerTrainBatchOp(selectedCols=["v"]).link_from(src)
    model = train.collect()
    # memory, file, and op references all normalize to the same table
    p = str(tmp_path / "m.ak")
    write_ak(p, model)
    for ref in (model, p, train, MemoryDataBridge(model),
                LocalFileDataBridge(p)):
        got = DirectReader.read(ref)
        assert list(got.col("key")) == list(model.col("key"))


def test_autocross_finds_interaction():
    rng = np.random.default_rng(0)
    n = 600
    a = rng.choice(["x", "y"], n)
    b = rng.choice(["p", "q"], n)
    c = rng.choice(["m", "n"], n)          # noise column
    # label is the XOR of a and b — invisible to marginals, visible to a#b
    label = ((a == "x") ^ (b == "p")).astype(int)
    rows = list(zip(a, b, c, label))
    src = MemSourceBatchOp(rows, "a string, b string, c string, label int")
    from alink_tpu.operator.batch import (AutoCrossBatchOp,
                                          AutoCrossPredictBatchOp)

    model = AutoCrossBatchOp(categoricalCols=["a", "b", "c"],
                             labelCol="label", numCross=1,
                             positiveLabelValueString="1").link_from(src)
    from alink_tpu.common.model import table_to_model
    meta, _ = table_to_model(model.collect())
    assert meta["crosses"] == [["a", "b"]]   # the XOR pair wins
    out = AutoCrossPredictBatchOp().link_from(model, src).collect()
    assert "cross_a_b" in out.names
    assert out.col("cross_a_b")[0] == f"{a[0]}#{b[0]}"

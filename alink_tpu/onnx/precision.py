"""Shared bf16 inference-policy helpers for the foreign-model converters.

One source of truth for the policy all ingest formats apply: float weights
load in the compute dtype, float inputs cast on device, float outputs
return fp32 (integer tensors pass through untouched).

The jit wrappers route through ``common/jitcache.cached_jit`` so two
converted models of the same graph family share ONE traced program (keyed
by the wrapped fn's code + captured weights and the policy dtype) instead
of rebuilding a ``jax.jit`` closure per conversion; converter fns whose
captured state cannot be content-keyed fall back to a per-call build.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np


def resolve_dtype(dtype) -> Optional[Any]:
    """None or an explicit fp32 request -> None (the fp32 parity path,
    which pins full-precision matmuls); anything else -> a dtype
    (jnp.dtype resolves 'bfloat16' through ml_dtypes)."""
    if dtype is None:
        return None
    import jax.numpy as jnp

    dt = jnp.dtype(dtype)
    return None if dt == jnp.float32 else dt


def cast_float_state(state: Dict[str, np.ndarray], dtype) -> Dict[str, Any]:
    """Cast the float entries of a weight/initializer dict to ``dtype``."""
    return {
        k: (np.asarray(v).astype(dtype)
            if np.issubdtype(np.asarray(v).dtype, np.floating) else v)
        for k, v in state.items()
    }


def _build_wrap_positional(fn, dtype_s: str):
    import jax
    import jax.numpy as jnp

    dtype = jnp.dtype(dtype_s)

    def wrapped(*args):
        cast = [a.astype(dtype)
                if jnp.issubdtype(a.dtype, jnp.floating) else a
                for a in map(jnp.asarray, args)]
        out = fn(*cast)
        return [o.astype(jnp.float32)
                if jnp.issubdtype(o.dtype, jnp.floating) else o
                for o in out]

    return jax.jit(wrapped)


def _build_wrap_named(fn, dtype_s: str):
    # positional form with the input-name tuple as a static: the program
    # cache counts call signatures positionally, so the kwargs surface
    # lives in _NamedAdapter, not the traced function
    import jax
    import jax.numpy as jnp

    dtype = jnp.dtype(dtype_s)

    def wrapped(names, *values):
        cast = {k: (v.astype(dtype)
                    if jnp.issubdtype(v.dtype, jnp.floating) else v)
                for k, v in ((k, jnp.asarray(v))
                             for k, v in zip(names, values))}
        out = fn(**cast)
        return {k: (v.astype(jnp.float32)
                    if jnp.issubdtype(v.dtype, jnp.floating) else v)
                for k, v in out.items()}

    return jax.jit(wrapped, static_argnums=0)


def _build_wrap_pinned_positional(fn):
    import jax

    def wrapped(*args):
        with jax.default_matmul_precision("highest"):
            return fn(*args)

    return jax.jit(wrapped)


def _build_wrap_pinned_named(fn):
    import jax

    def wrapped(names, *values):
        with jax.default_matmul_precision("highest"):
            return fn(**dict(zip(names, values)))

    return jax.jit(wrapped, static_argnums=0)


class _NamedAdapter:
    """kwargs façade over a positional program (the ProgramCache counts
    call signatures positionally; the sorted key tuple rides as a jit
    static, so any one key set traces once)."""

    __slots__ = ("_prog",)

    def __init__(self, prog):
        self._prog = prog

    def __call__(self, **inputs):
        names = tuple(sorted(inputs))
        return self._prog(names, *(inputs[k] for k in names))


def _cached_wrap(kernel_id: str, builder, fn, *static):
    from ..common.jitcache import Unkeyable, cached_jit, fn_content_key

    try:
        return cached_jit(kernel_id, builder, fn, *static,
                          key_extra=fn_content_key(fn))
    except Unkeyable:
        # the converter fn closes over state the key cannot digest: fall
        # back to the per-call build — correctness first, reuse elsewhere
        return builder(fn, *static)


def wrap_positional(fn, dtype):
    """jit-wrap a positional fn returning a LIST of arrays under the policy."""
    return _cached_wrap("onnx.wrap_positional", _build_wrap_positional,
                        fn, str(np.dtype(dtype)) if dtype is not None
                        else "float32")


def wrap_named(fn, dtype):
    """jit-wrap a kwargs fn returning a DICT of arrays under the policy."""
    return _NamedAdapter(
        _cached_wrap("onnx.wrap_named", _build_wrap_named,
                     fn, str(np.dtype(dtype)) if dtype is not None
                     else "float32"))


def wrap_pinned_positional(fn):
    """jit-wrap a positional fn with the fp32 numerics-parity pin (full-
    precision matmuls, so TPU results match the source runtime)."""
    return _cached_wrap("onnx.wrap_pinned_positional",
                        _build_wrap_pinned_positional, fn)


def wrap_pinned_named(fn):
    """Named-argument twin of :func:`wrap_pinned_positional`."""
    return _NamedAdapter(
        _cached_wrap("onnx.wrap_pinned_named",
                     _build_wrap_pinned_named, fn))

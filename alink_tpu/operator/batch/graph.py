"""Graph algorithm operators.

Capability parity with the reference graph package (reference:
core/src/main/java/com/alibaba/alink/operator/batch/graph/
PageRankBatchOp.java, ConnectedComponentsBatchOp.java, KCoreBatchOp.java,
LouvainBatchOp.java, TriangleListBatchOp.java,
VertexClusterCoefficientBatchOp.java, EdgeClusterCoefficientBatchOp.java,
CommonNeighborsBatchOp.java, SingleSourceShortestPathBatchOp.java,
CommunityDetectionClusterBatchOp.java, ModularityCalBatchOp.java).

All ops take an edge table (sourceCol, targetCol[, weightCol]) and run on the
superstep engine in graph/engine.py (segment-reduce supersteps compiled once).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ...common.mtable import AlinkTypes, MTable, TableSchema
from ...common.params import MinValidator, ParamInfo
from ...graph.engine import (
    MemoryGraph,
    connected_components,
    kcore,
    label_propagation,
    louvain,
    modularity,
    pagerank,
    sssp,
    triangles,
)
from .base import BatchOperator


class _HasGraphCols:
    SOURCE_COL = ParamInfo("sourceCol", str, default="source",
                           aliases=("edgeSourceCol",))
    TARGET_COL = ParamInfo("targetCol", str, default="target",
                           aliases=("edgeTargetCol",))
    WEIGHT_COL = ParamInfo("weightCol", str, aliases=("edgeWeightCol",))

    def _graph(self, t: MTable, directed: bool = False) -> MemoryGraph:
        return MemoryGraph.from_table(
            t, self.get(self.SOURCE_COL), self.get(self.TARGET_COL),
            self.get(self.WEIGHT_COL), directed=directed)


_VERTEX_DOUBLE = TableSchema(["vertex", "value"],
                             [AlinkTypes.STRING, AlinkTypes.DOUBLE])
_VERTEX_LONG = TableSchema(["vertex", "value"],
                           [AlinkTypes.STRING, AlinkTypes.LONG])


class PageRankBatchOp(BatchOperator, _HasGraphCols):
    """(reference: PageRankBatchOp.java)"""

    DAMPING_FACTOR = ParamInfo("dampingFactor", float, default=0.85)
    MAX_ITER = ParamInfo("maxIter", int, default=100, validator=MinValidator(1))
    EPSILON = ParamInfo("epsilon", float, default=1e-6)

    _min_inputs = 1
    _max_inputs = 1

    def _execute_impl(self, t: MTable) -> MTable:
        g = self._graph(t, directed=True)
        pr = pagerank(g, self.get(self.DAMPING_FACTOR),
                      self.get(self.MAX_ITER), self.get(self.EPSILON))
        return MTable({"vertex": g.labels.astype(str),
                       "value": pr.astype(np.float64)}, _VERTEX_DOUBLE)

    def _out_schema(self, in_schema):
        return _VERTEX_DOUBLE


class ConnectedComponentsBatchOp(BatchOperator, _HasGraphCols):
    """(reference: ConnectedComponentsBatchOp.java)"""

    MAX_ITER = ParamInfo("maxIter", int, default=200, validator=MinValidator(1))

    _min_inputs = 1
    _max_inputs = 1

    def _execute_impl(self, t: MTable) -> MTable:
        g = self._graph(t)
        comp = connected_components(g, self.get(self.MAX_ITER))
        return MTable({"vertex": g.labels.astype(str),
                       "value": comp.astype(np.int64)}, _VERTEX_LONG)

    def _out_schema(self, in_schema):
        return _VERTEX_LONG


class KCoreBatchOp(BatchOperator, _HasGraphCols):
    """Edges of the k-core subgraph (reference: KCoreBatchOp.java)."""

    K = ParamInfo("k", int, default=3, validator=MinValidator(1))

    _min_inputs = 1
    _max_inputs = 1

    def _execute_impl(self, t: MTable) -> MTable:
        g = self._graph(t)
        alive = kcore(g, self.get(self.K))
        half = len(g.src) // 2  # undirected edge list duplicated both ways
        src, dst = g.src[:half], g.dst[:half]
        keep = alive[src] & alive[dst]
        return MTable(
            {"source": g.labels[src[keep]].astype(str),
             "target": g.labels[dst[keep]].astype(str)},
            TableSchema(["source", "target"],
                        [AlinkTypes.STRING, AlinkTypes.STRING]))

    def _out_schema(self, in_schema):
        return TableSchema(["source", "target"],
                           [AlinkTypes.STRING, AlinkTypes.STRING])


class SingleSourceShortestPathBatchOp(BatchOperator, _HasGraphCols):
    """(reference: SingleSourceShortestPathBatchOp.java)"""

    SOURCE_POINT = ParamInfo("sourcePoint", str, optional=False)

    _min_inputs = 1
    _max_inputs = 1

    def _execute_impl(self, t: MTable) -> MTable:
        g = self._graph(t)
        label_list = g.labels.astype(str).tolist()
        source = label_list.index(str(self.get(self.SOURCE_POINT)))
        dist = sssp(g, source)
        return MTable({"vertex": g.labels.astype(str),
                       "value": dist.astype(np.float64)}, _VERTEX_DOUBLE)

    def _out_schema(self, in_schema):
        return _VERTEX_DOUBLE


class LouvainBatchOp(BatchOperator, _HasGraphCols):
    """(reference: LouvainBatchOp.java)"""

    _min_inputs = 1
    _max_inputs = 1

    def _execute_impl(self, t: MTable) -> MTable:
        g = self._graph(t)
        comm = louvain(g)
        return MTable({"vertex": g.labels.astype(str),
                       "value": comm.astype(np.int64)}, _VERTEX_LONG)

    def _out_schema(self, in_schema):
        return _VERTEX_LONG


class CommunityDetectionClusterBatchOp(BatchOperator, _HasGraphCols):
    """Label-propagation communities (reference:
    CommunityDetectionClusterBatchOp.java)."""

    MAX_ITER = ParamInfo("maxIter", int, default=50, validator=MinValidator(1))

    _min_inputs = 1
    _max_inputs = 1

    def _execute_impl(self, t: MTable) -> MTable:
        g = self._graph(t)
        comm = label_propagation(g, max_iter=self.get(self.MAX_ITER))
        return MTable({"vertex": g.labels.astype(str),
                       "value": comm.astype(np.int64)}, _VERTEX_LONG)

    def _out_schema(self, in_schema):
        return _VERTEX_LONG


class ModularityCalBatchOp(BatchOperator, _HasGraphCols):
    """Modularity of a partition; ``link_from(edges, vertex_communities)``
    (reference: ModularityCalBatchOp.java)."""

    VERTEX_COL = ParamInfo("vertexCol", str, default="vertex")
    VERTEX_COMMUNITY_COL = ParamInfo("vertexCommunityCol", str, default="value")

    _min_inputs = 2
    _max_inputs = 2

    def _execute_impl(self, edges: MTable, comm_t: MTable) -> MTable:
        g = self._graph(edges)
        label_to_comm = {
            str(v): int(c) for v, c in zip(
                comm_t.col(self.get(self.VERTEX_COL)),
                comm_t.col(self.get(self.VERTEX_COMMUNITY_COL)))}
        comm = np.asarray([label_to_comm[str(v)]
                           for v in g.labels.astype(str)], np.int64)
        q = modularity(g, comm)
        return MTable({"modularity": [q]},
                      TableSchema(["modularity"], [AlinkTypes.DOUBLE]))

    def _out_schema(self, *in_schemas):
        return TableSchema(["modularity"], [AlinkTypes.DOUBLE])


_TRIANGLE_SCHEMA = TableSchema(
    ["node1", "node2", "node3"],
    [AlinkTypes.STRING, AlinkTypes.STRING, AlinkTypes.STRING])


class TriangleListBatchOp(BatchOperator, _HasGraphCols):
    """(reference: TriangleListBatchOp.java)"""

    _min_inputs = 1
    _max_inputs = 1

    def _execute_impl(self, t: MTable) -> MTable:
        g = self._graph(t)
        tris, _ = triangles(g)
        lab = g.labels.astype(str)
        rows = [(lab[a], lab[b], lab[c]) for a, b, c in tris]
        if not rows:
            return MTable({"node1": np.asarray([], object),
                           "node2": np.asarray([], object),
                           "node3": np.asarray([], object)}, _TRIANGLE_SCHEMA)
        return MTable.from_rows(rows, _TRIANGLE_SCHEMA)

    def _out_schema(self, in_schema):
        return _TRIANGLE_SCHEMA


class VertexClusterCoefficientBatchOp(BatchOperator, _HasGraphCols):
    """Per-vertex clustering coefficient (reference:
    VertexClusterCoefficientBatchOp.java)."""

    _min_inputs = 1
    _max_inputs = 1

    def _execute_impl(self, t: MTable) -> MTable:
        g = self._graph(t)
        _, counts = triangles(g)
        adj = g.adjacency_sets()
        deg = np.asarray([len(adj[i]) for i in range(g.num_vertices)])
        possible = deg * (deg - 1) / 2.0
        coef = np.where(possible > 0, counts / np.maximum(possible, 1), 0.0)
        return MTable({"vertex": g.labels.astype(str),
                       "value": coef.astype(np.float64)}, _VERTEX_DOUBLE)

    def _out_schema(self, in_schema):
        return _VERTEX_DOUBLE


class EdgeClusterCoefficientBatchOp(BatchOperator, _HasGraphCols):
    """Per-edge: common neighbors / min(deg)-1 (reference:
    EdgeClusterCoefficientBatchOp.java)."""

    _min_inputs = 1
    _max_inputs = 1

    def _execute_impl(self, t: MTable) -> MTable:
        g = self._graph(t)
        adj = g.adjacency_sets()
        half = len(g.src) // 2
        rows = []
        lab = g.labels.astype(str)
        for a, b in zip(g.src[:half], g.dst[:half]):
            a, b = int(a), int(b)
            cn = len(adj[a] & adj[b])
            denom = min(len(adj[a]), len(adj[b])) - 1
            coef = cn / denom if denom > 0 else 0.0
            rows.append((lab[a], lab[b], float(cn), float(coef)))
        schema = TableSchema(
            ["source", "target", "commonNeighbors", "coefficient"],
            [AlinkTypes.STRING, AlinkTypes.STRING, AlinkTypes.DOUBLE,
             AlinkTypes.DOUBLE])
        return MTable.from_rows(rows, schema)

    def _out_schema(self, in_schema):
        return TableSchema(
            ["source", "target", "commonNeighbors", "coefficient"],
            [AlinkTypes.STRING, AlinkTypes.STRING, AlinkTypes.DOUBLE,
             AlinkTypes.DOUBLE])


class CommonNeighborsBatchOp(BatchOperator, _HasGraphCols):
    """Common neighbors of each input pair (reference:
    CommonNeighborsBatchOp.java)."""

    _min_inputs = 1
    _max_inputs = 1

    def _execute_impl(self, t: MTable) -> MTable:
        g = self._graph(t)
        adj = g.adjacency_sets()
        half = len(g.src) // 2
        lab = g.labels.astype(str)
        rows = []
        for a, b in zip(g.src[:half], g.dst[:half]):
            a, b = int(a), int(b)
            common = sorted(adj[a] & adj[b])
            rows.append((lab[a], lab[b],
                         " ".join(lab[c] for c in common),
                         float(len(common))))
        schema = TableSchema(
            ["source", "target", "neighbors", "cnt"],
            [AlinkTypes.STRING, AlinkTypes.STRING, AlinkTypes.STRING,
             AlinkTypes.DOUBLE])
        return MTable.from_rows(rows, schema)

    def _out_schema(self, in_schema):
        return TableSchema(
            ["source", "target", "neighbors", "cnt"],
            [AlinkTypes.STRING, AlinkTypes.STRING, AlinkTypes.STRING,
             AlinkTypes.DOUBLE])


class MultiSourceShortestPathBatchOp(BatchOperator, _HasGraphCols):
    """Distance to the NEAREST of several sources, plus which root won
    (reference: MultiSourceShortestPathBatchOp.java). Implementation: one
    SSSP run per root with a host-side min-merge — O(|roots|) superstep
    runs; fine for the handful of roots the op is used with."""

    SOURCE_POINTS = ParamInfo("sourcePoints", list, optional=False,
                              aliases=("sourcePoint",))


    _min_inputs = 1
    _max_inputs = 1

    _SCHEMA = TableSchema(["vertex", "value", "root"],
                          [AlinkTypes.STRING, AlinkTypes.DOUBLE,
                           AlinkTypes.STRING])

    def _execute_impl(self, t: MTable) -> MTable:
        g = self._graph(t)
        label_list = g.labels.astype(str).tolist()
        srcs = [label_list.index(str(s))
                for s in self.get(self.SOURCE_POINTS)]
        n = len(g.labels)
        dist = np.full(n, np.inf)
        root = np.full(n, -1, np.int64)
        for s in srcs:
            d = sssp(g, s)
            better = d < dist
            dist[better] = d[better]
            root[better] = s
        root_labels = np.asarray(
            [g.labels[r] if r >= 0 else None for r in root], object)
        return MTable({"vertex": g.labels.astype(str),
                       "value": dist.astype(np.float64),
                       "root": root_labels}, self._SCHEMA)

    def _out_schema(self, in_schema):
        return self._SCHEMA


class TreeDepthBatchOp(BatchOperator, _HasGraphCols):
    """Depth of every vertex in a forest of rooted trees (reference:
    TreeDepthBatchOp.java — roots are vertices with no incoming edge;
    depth 0 at the root)."""

    _min_inputs = 1
    _max_inputs = 1

    _SCHEMA = TableSchema(["vertex", "root", "value"],
                          [AlinkTypes.STRING, AlinkTypes.STRING,
                           AlinkTypes.LONG])

    def _execute_impl(self, t: MTable) -> MTable:
        g = self._graph(t, directed=True)
        n = len(g.labels)
        parents = np.bincount(g.dst, minlength=n)
        from ...common.exceptions import AkIllegalDataException

        if (parents > 1).any():
            bad = g.labels[int(np.argmax(parents))]
            raise AkIllegalDataException(
                f"vertex {bad!r} has {int(parents.max())} parents — "
                "TreeDepth needs a forest")
        has_parent = parents > 0
        depth = np.full(n, -1, np.int64)
        root = np.arange(n)
        depth[~has_parent] = 0
        # BFS supersteps over the edge list (vectorized frontier expand)
        for _ in range(n):
            src_known = depth[g.src] >= 0
            cand = g.dst[src_known]
            new = depth[cand] < 0
            if not new.any():
                break
            depth[cand[new]] = depth[g.src[src_known]][new] + 1
            root[cand[new]] = root[g.src[src_known]][new]
        if (depth < 0).any():
            raise AkIllegalDataException(
                "graph contains a cycle or unreachable vertex — TreeDepth "
                "needs a forest")
        return MTable({"vertex": g.labels.astype(str),
                       "root": g.labels[root].astype(str),
                       "value": depth}, self._SCHEMA)

    def _out_schema(self, in_schema):
        return self._SCHEMA


class VertexNeighborSearchBatchOp(BatchOperator, _HasGraphCols):
    """Subgraph within K hops of the given vertices (reference:
    VertexNeighborSearchBatchOp.java — emits the induced edge list)."""

    SOURCES = ParamInfo("sources", list, optional=False,
                        aliases=("vertices",))
    DEPTH = ParamInfo("depth", int, default=1, validator=MinValidator(1))

    _min_inputs = 1
    _max_inputs = 1

    _SCHEMA = TableSchema(["source", "target"],
                          [AlinkTypes.STRING, AlinkTypes.STRING])

    def _execute_impl(self, t: MTable) -> MTable:
        g = self._graph(t)
        label_list = g.labels.astype(str).tolist()
        n = len(g.labels)
        seen = np.zeros(n, bool)
        for s in self.get(self.SOURCES):
            seen[label_list.index(str(s))] = True
        for _ in range(int(self.get(self.DEPTH))):
            frontier = seen[g.src]
            seen[g.dst[frontier]] = True
        half = len(g.src) // 2
        src, dst = g.src[:half], g.dst[:half]
        keep = seen[src] & seen[dst]
        return MTable({"source": g.labels[src[keep]].astype(str),
                       "target": g.labels[dst[keep]].astype(str)},
                      self._SCHEMA)

    def _out_schema(self, in_schema):
        return self._SCHEMA
